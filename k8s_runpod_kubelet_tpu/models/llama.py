"""Llama-3-family decoder, TPU-first.

Design choices (vs a torch transliteration):
- **Stacked layer params + lax.scan**: every layer leaf carries a leading
  (n_layers, ...) axis and the forward pass scans one remat'd block over it —
  compile time stays flat in depth and the block is pipeline-ready.
- **Functional params** (plain pytree): shardings are explicit NamedShardings
  from parallel/sharding.py's logical rules; orbax checkpoints the tree as-is.
- **bf16 compute, f32 params** by default; all matmuls are MXU-shaped.
- **flash/ring attention** from ops/ — ring engages when the mesh has a seq
  axis (long context, SURVEY.md §5.7).
- **KV-cache decode** path for the serving engine (JetStream-style, config 5).

The same class covers Llama-3-8B/70B and Gemma-7B (explicit head_dim,
tied/untied embeddings, GeGLU vs SwiGLU, sqrt(E) embedding scaling,
zero-centered RMSNorm, optional logit softcap) — see the config constructors.
"""

from __future__ import annotations

import contextvars
import dataclasses
import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ..ops import (apply_rope, flash_attention, paged_attention,
                   ring_attention, rms_norm, rope_frequencies)
from ..ops.attention import (paged_attention_mla, paged_attention_mla_quant,
                             paged_attention_multi,
                             paged_attention_multi_mla,
                             paged_attention_multi_mla_quant,
                             paged_attention_multi_quant,
                             paged_attention_quant)
from .moe import moe_mlp
from ..parallel.mesh import AXES
from ..parallel.pipeline import pipeline_spmd, pipeline_stages
from ..parallel.sharding import logical_sharding, shard_logical

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    name: str = "tiny"
    vocab_size: int = 32000
    embed_dim: int = 256
    n_layers: int = 2
    n_heads: int = 4
    n_kv_heads: int = 2
    head_dim: Optional[int] = None      # default embed_dim // n_heads (Gemma differs)
    mlp_dim: int = 688
    max_seq_len: int = 2048
    rope_theta: float = 500_000.0
    rope_scaling: Optional[dict] = None
    norm_eps: float = 1e-5
    # Mistral-style sliding-window attention: each position attends only the
    # last W tokens (None = full causal). The flash kernels skip blocks
    # outside the band, so long-context cost is O(S*W); decode masks the
    # cache the same way.
    sliding_window: Optional[int] = None
    # Gemma-2/3-style local/global interleave: layers repeat in groups of
    # ``sliding_window_pattern``; within a group the LAST layer is global
    # (full causal) and the rest use ``sliding_window``. 1 = every layer
    # windowed (Mistral). Implemented by scanning over layer GROUPS with the
    # per-sublayer window static inside the body — no data-dependent masks.
    sliding_window_pattern: int = 1
    # Gemma-2: attention scores pass cap*tanh(s/cap) before the causal mask
    attn_logit_softcap: Optional[float] = None
    # Gemma-2: q is scaled by this**-0.5 instead of head_dim**-0.5
    query_pre_attn_scalar: Optional[float] = None
    # Gemma-2 "sandwich" norms: extra RMSNorm on each sublayer OUTPUT
    # (post-attention and post-MLP), before the residual add
    post_norms: bool = False
    # Gemma-3: RMSNorm over head_dim on q and k (per layer, shared across
    # heads), applied BEFORE RoPE
    qk_norm: bool = False
    # Gemma-3: local (windowed) sublayers rotate with this RoPE base while
    # global sublayers use rope_theta (+ rope_scaling); None = one base
    rope_local_theta: Optional[float] = None
    tie_embeddings: bool = False
    mlp_activation: str = "silu"        # "silu" (SwiGLU) | "gelu_tanh" (GeGLU, Gemma)
    embed_scale: bool = False           # scale embeddings by sqrt(embed_dim) (Gemma)
    logit_softcap: Optional[float] = None  # tanh soft cap on lm-head logits (Gemma-2)
    norm_zero_centered: bool = False    # RMSNorm weight stored as w, applied as (1+w) (Gemma)
    qkv_bias: bool = False              # bias on q/k/v projections (Qwen2)
    # sparse MoE (Mixtral family): n_experts=0 means dense MLP
    n_experts: int = 0
    n_experts_per_tok: int = 2
    capacity_factor: float = 1.25
    # True (Mixtral): renormalize the top-k softmax weights to sum to 1;
    # False (DeepSeek-V2-Lite norm_topk_prob=false): combine with the raw
    # softmax-over-all-experts probabilities of the selected k
    router_norm_topk: bool = True
    # DeepSeek-V3 routing: sigmoid scores with an aux-free-balancing
    # correction bias (a PARAM leaf "router_bias", updated outside the
    # gradient) and group-limited selection over router_n_group groups,
    # keeping router_topk_group; combine weights scale by
    # routed_scaling_factor. moe.route_top_k_v3 is the exact math.
    router_sigmoid_bias: bool = False
    router_n_group: int = 0
    router_topk_group: int = 0
    routed_scaling_factor: float = 1.0
    router_aux_coef: float = 0.02       # load-balance loss coefficient
    router_z_coef: float = 1e-3         # router z-loss coefficient
    # pipeline parallelism: microbatch count when the mesh has a stage axis
    # (default = n_stages; more microbatches shrink the GPipe bubble)
    pipeline_microbatches: Optional[int] = None
    dtype: Any = jnp.bfloat16           # activation/compute dtype
    param_dtype: Any = jnp.float32
    remat: bool = True
    # "full": recompute the whole layer in backward (min HBM, +1 fwd of
    # FLOPs); "dots": save matmul outputs, recompute elementwise (MaxText's
    # default trade at scale — needs the activation HBM); "none": save all.
    remat_policy: str = "full"
    # sequence-parallel attention chunks through the streamed Pallas
    # kernels ("ring flash attention") instead of the XLA einsum
    # recurrence: per-chunk scores never materialize in HBM and windowed
    # rings truncate their rotation. CPU-parity-tested (interpret mode);
    # default OFF until verified on real TPU — flip per ROUND3_NOTES.
    ring_flash: bool = False
    # Multi-head Latent Attention (DeepSeek-V2, arXiv:2405.04434): set
    # mla_latent_dim (rank r) to replace K/V projections with a shared
    # latent c = h @ w_dkv; per-head K/V are up-projections of c, so the
    # cache stores (r + mla_rope_dim) floats per position instead of
    # 2*n_kv_heads*head_dim — 8-57x smaller. Decode runs the ABSORBED form
    # (w_uk folded into q, w_uv into the output): per step it reads the
    # latent cache, never materialized K/V. RoPE is decoupled: q carries an
    # extra mla_rope_dim tail scored against ONE shared rotated key per
    # token (rotation does not commute with the up-projection). MLA ignores
    # n_kv_heads and excludes sliding_window/qk_norm/qkv_bias (DeepSeek
    # uses none of them). See ops/mla.py for the self-contained op.
    mla_latent_dim: Optional[int] = None
    mla_rope_dim: int = 64
    # DeepSeek q_lora_rank: low-rank q projection (q = norm(h @ wq_a) @
    # wq_b with q_a_layernorm between) — V2-full/V3 use it (1536); None =
    # full-rank q (V2-Lite).
    mla_q_lora_rank: Optional[int] = None
    # DeepSeek-MoE: this many always-on "shared" experts run as a dense
    # MLP of width n_shared_experts * mlp_dim alongside the routed experts
    # (their output is added, router ignores them). 0 = plain MoE/dense.
    n_shared_experts: int = 0
    # DeepSeek first_k_dense_replace: the first k layers use a DENSE MLP
    # (width dense_prefix_mlp_dim, default mlp_dim) instead of the MoE —
    # stored as a separate "prefix_layers" stack and scanned before the
    # main layers. MLA-only (the windowed/ring cache machinery never
    # composes with a prefix); V2-Lite: 1 dense layer at width 10944.
    n_dense_prefix: int = 0
    dense_prefix_mlp_dim: Optional[int] = None

    @property
    def head_dim_(self) -> int:
        return self.head_dim or self.embed_dim // self.n_heads

    @property
    def is_mla(self) -> bool:
        return self.mla_latent_dim is not None

    def prefix_cfg(self) -> "LlamaConfig":
        """Config view of the dense-prefix layers: same attention, dense
        MLP at dense_prefix_mlp_dim, n_layers = the prefix length. The
        layer machinery (blocks, shapes, axes) runs unchanged on it."""
        return dataclasses.replace(
            self, n_layers=self.n_dense_prefix, n_experts=0,
            n_shared_experts=0,
            mlp_dim=self.dense_prefix_mlp_dim or self.mlp_dim,
            n_dense_prefix=0, dense_prefix_mlp_dim=None)

    def main_cfg(self) -> "LlamaConfig":
        """Config view of the main (post-prefix) layer stack."""
        if not self.n_dense_prefix:
            return self
        return dataclasses.replace(
            self, n_layers=self.n_layers - self.n_dense_prefix,
            n_dense_prefix=0, dense_prefix_mlp_dim=None)

    def validate_mla(self) -> None:
        if self.n_dense_prefix:
            if not self.is_mla or not self.n_experts:
                raise ValueError("n_dense_prefix models the DeepSeek shape: "
                                 "MLA attention over a MoE body")
            if self.n_dense_prefix >= self.n_layers:
                raise ValueError(f"n_dense_prefix {self.n_dense_prefix} must "
                                 f"leave MoE layers (n_layers "
                                 f"{self.n_layers})")
        if self.mla_q_lora_rank is not None and not self.is_mla:
            raise ValueError("mla_q_lora_rank requires MLA "
                             "(set mla_latent_dim); on a plain-attention "
                             "config the field would silently do nothing")
        if self.router_sigmoid_bias:
            ng, tg = self.router_n_group, self.router_topk_group
            if not self.n_experts:
                raise ValueError("router_sigmoid_bias needs a MoE config "
                                 "(n_experts > 0); on a dense MLP it would "
                                 "silently do nothing")
            if ng <= 0 or tg <= 0 or tg > ng or self.n_experts % ng:
                raise ValueError(
                    f"V3 routing needs 0 < router_topk_group "
                    f"({tg}) <= router_n_group ({ng}) and n_experts "
                    f"({self.n_experts}) divisible by router_n_group")
            if self.n_experts_per_tok > (self.n_experts // ng) * tg:
                raise ValueError(
                    f"n_experts_per_tok {self.n_experts_per_tok} exceeds "
                    f"the {(self.n_experts // ng) * tg} experts the "
                    "group-limited selection keeps eligible")
        if not self.is_mla:
            return
        bad = [f for f, on in (("sliding_window",
                                self.sliding_window is not None),
                               ("qk_norm", self.qk_norm),
                               ("qkv_bias", self.qkv_bias),
                               ("attn_logit_softcap",
                                self.attn_logit_softcap is not None),
                               ("query_pre_attn_scalar",
                                self.query_pre_attn_scalar is not None))
               if on]
        if bad:
            raise ValueError(f"MLA does not compose with {bad} "
                             "(DeepSeek-V2 uses none of them; the MLA "
                             "paths score at (head_dim+rope_dim)**-0.5 "
                             "with no softcap — rejecting beats silently "
                             "ignoring the config)")

    @property
    def sm_scale(self) -> float:
        base = (self.query_pre_attn_scalar
                if self.query_pre_attn_scalar is not None else self.head_dim_)
        return base ** -0.5

    def layer_windows(self) -> tuple[Optional[int], ...]:
        """Static per-sublayer window for one scan group (len = pattern)."""
        p = self.sliding_window_pattern
        if self.sliding_window is None:
            return (None,) * p
        if p == 1:
            return (self.sliding_window,)
        return tuple(self.sliding_window if j != p - 1 else None
                     for j in range(p))

    @property
    def param_count(self) -> int:
        e, m, l, v = self.embed_dim, self.mlp_dim, self.n_layers, self.vocab_size
        hd = self.head_dim_
        if self.is_mla:
            r, dr, h = self.mla_latent_dim, self.mla_rope_dim, self.n_heads
            qr = self.mla_q_lora_rank
            q_params = (e * qr + qr + qr * h * (hd + dr)  # wq_a/norm/wq_b
                        if qr is not None
                        else e * h * (hd + dr))           # full-rank wq
            attn = (q_params
                    + e * (r + dr)         # w_dkv
                    + r                    # c_norm (kv_a_layernorm)
                    + 2 * r * h * hd       # w_uk, w_uv
                    + h * hd * e)          # w_o
        else:
            attn = e * hd * (self.n_heads * 2 + self.n_kv_heads * 2)
        if self.qkv_bias:
            attn += hd * (self.n_heads + 2 * self.n_kv_heads)
        if self.qk_norm:
            attn += 2 * hd
        if self.n_experts:
            mlp = 3 * e * m * self.n_experts + e * self.n_experts  # experts + router
            mlp += 3 * e * m * self.n_shared_experts
            if self.router_sigmoid_bias:
                mlp += self.n_experts   # e_score_correction_bias
        else:
            mlp = 3 * e * m
        norms = (4 if self.post_norms else 2) * e
        embed = v * e * (1 if self.tie_embeddings else 2)
        k = self.n_dense_prefix
        if k:
            mlp_prefix = 3 * e * (self.dense_prefix_mlp_dim or m)
            layer_total = ((l - k) * (attn + mlp + norms)
                           + k * (attn + mlp_prefix + norms))
        else:
            layer_total = l * (attn + mlp + norms)
        return layer_total + embed + e


def llama3_8b() -> LlamaConfig:
    return LlamaConfig(name="llama3-8b", vocab_size=128256, embed_dim=4096,
                       n_layers=32, n_heads=32, n_kv_heads=8, mlp_dim=14336,
                       max_seq_len=8192, rope_theta=500_000.0)


def llama3_70b() -> LlamaConfig:
    return LlamaConfig(name="llama3-70b", vocab_size=128256, embed_dim=8192,
                       n_layers=80, n_heads=64, n_kv_heads=8, mlp_dim=28672,
                       max_seq_len=8192, rope_theta=500_000.0)


def llama31_8b() -> LlamaConfig:
    # Llama-3.1-8B: the 3.0 backbone at 128k context via the NTK-aware
    # frequency warp (ops/rope.py rope_frequencies scaling branch).
    return LlamaConfig(name="llama31-8b", vocab_size=128256, embed_dim=4096,
                       n_layers=32, n_heads=32, n_kv_heads=8, mlp_dim=14336,
                       max_seq_len=131072, rope_theta=500_000.0,
                       rope_scaling={"factor": 8.0, "low_freq_factor": 1.0,
                                     "high_freq_factor": 4.0,
                                     "original_max_position": 8192})


def gemma_7b() -> LlamaConfig:
    # Gemma-7B, faithfully: MHA with wide head_dim, GeGLU MLP, embeddings
    # scaled by sqrt(embed_dim), zero-centered RMSNorm, tied lm head.
    return LlamaConfig(name="gemma-7b", vocab_size=256000, embed_dim=3072,
                       n_layers=28, n_heads=16, n_kv_heads=16, head_dim=256,
                       mlp_dim=24576, max_seq_len=8192, rope_theta=10_000.0,
                       norm_eps=1e-6, tie_embeddings=True,
                       mlp_activation="gelu_tanh", embed_scale=True,
                       norm_zero_centered=True)


def gemma2_9b() -> LlamaConfig:
    # Gemma-2-9B: alternating local(4096)/global attention (even layers
    # local), tanh soft caps on attention scores (50) and final logits (30),
    # sandwich norms around both sublayers, GQA with wide heads.
    return LlamaConfig(name="gemma2-9b", vocab_size=256000, embed_dim=3584,
                       n_layers=42, n_heads=16, n_kv_heads=8, head_dim=256,
                       mlp_dim=14336, max_seq_len=8192, rope_theta=10_000.0,
                       norm_eps=1e-6, tie_embeddings=True,
                       mlp_activation="gelu_tanh", embed_scale=True,
                       norm_zero_centered=True,
                       sliding_window=4096, sliding_window_pattern=2,
                       attn_logit_softcap=50.0, logit_softcap=30.0,
                       query_pre_attn_scalar=256.0, post_norms=True)


def gemma3_12b() -> LlamaConfig:
    # Gemma-3-12B (text): 5 local(1024) : 1 global interleave, per-kind RoPE
    # bases (local 10k, global 1M with linear x8 scaling), RMSNorm on q/k,
    # sandwich norms; no tanh soft caps (qk-norm replaced them).
    return LlamaConfig(name="gemma3-12b", vocab_size=262208, embed_dim=3840,
                       n_layers=48, n_heads=16, n_kv_heads=8, head_dim=256,
                       mlp_dim=15360, max_seq_len=32768,
                       rope_theta=1_000_000.0, rope_local_theta=10_000.0,
                       rope_scaling={"rope_type": "linear", "factor": 8.0},
                       norm_eps=1e-6, tie_embeddings=True,
                       mlp_activation="gelu_tanh", embed_scale=True,
                       norm_zero_centered=True,
                       sliding_window=1024, sliding_window_pattern=6,
                       query_pre_attn_scalar=256.0, post_norms=True,
                       qk_norm=True)


def mixtral_8x7b() -> LlamaConfig:
    # Mixtral-8x7B: Mistral-7B backbone with 8-expert top-2 sparse MLPs.
    return LlamaConfig(name="mixtral-8x7b", vocab_size=32000, embed_dim=4096,
                       n_layers=32, n_heads=32, n_kv_heads=8, mlp_dim=14336,
                       max_seq_len=32768, rope_theta=1_000_000.0,
                       n_experts=8, n_experts_per_tok=2)


def mistral_7b() -> LlamaConfig:
    # Mistral-7B-v0.1: Llama-shaped GQA decoder with 4096-token sliding-
    # window attention.
    return LlamaConfig(name="mistral-7b", vocab_size=32000, embed_dim=4096,
                       n_layers=32, n_heads=32, n_kv_heads=8, mlp_dim=14336,
                       max_seq_len=32768, rope_theta=10_000.0,
                       sliding_window=4096)


def qwen2_7b() -> LlamaConfig:
    # Qwen2-7B: Llama-style GQA decoder with biased q/k/v projections.
    return LlamaConfig(name="qwen2-7b", vocab_size=152064, embed_dim=3584,
                       n_layers=28, n_heads=28, n_kv_heads=4, mlp_dim=18944,
                       max_seq_len=32768, rope_theta=1_000_000.0,
                       norm_eps=1e-6, qkv_bias=True)


def deepseek_v2_lite() -> LlamaConfig:
    """DeepSeek-V2-Lite-class: MLA (latent 512 + decoupled RoPE 64, heads
    16x128) over a DeepSeek-MoE MLP (64 routed experts top-6 + 2 shared,
    expert width 1408), with the real checkpoint's FIRST layer dense at
    width 10944 (first_k_dense_replace=1 -> n_dense_prefix) and full-rank
    q (true for V2-Lite: q_lora_rank is null). HF checkpoints load with
    logits parity (tests/test_hf_convert.py TestDeepseekV2Parity).
    max_seq_len matches the checkpoint's max_position_embeddings: 163840
    = YaRN factor 40 x original 4096 — a shorter value here would
    silently cap the context the YaRN tables were scaled for."""
    return LlamaConfig(name="deepseek-v2-lite", vocab_size=102400,
                       embed_dim=2048, n_layers=27, n_heads=16,
                       n_kv_heads=16, head_dim=128, mlp_dim=1408,
                       max_seq_len=163840, rope_theta=10_000.0,
                       rope_scaling={"rope_type": "yarn", "factor": 40.0,
                                     "beta_fast": 32, "beta_slow": 1,
                                     "mscale": 0.707,
                                     "mscale_all_dim": 0.707,
                                     "original_max_position_embeddings":
                                         4096},
                       norm_eps=1e-6,
                       mla_latent_dim=512, mla_rope_dim=64,
                       n_experts=64, n_experts_per_tok=6,
                       n_shared_experts=2, router_norm_topk=False,
                       n_dense_prefix=1, dense_prefix_mlp_dim=10944)


def deepseek_v3() -> LlamaConfig:
    """DeepSeek-V3/R1-class: the V2 MLA (latent 512 + rope 64, heads
    128x128, low-rank q 1536) with V3's sigmoid-scored, bias-corrected,
    group-limited routing (256 experts top-8, 8 groups keep 4, scaling
    2.5, renormalized) + 1 shared expert; first 3 layers dense at 18432.
    671B total — a MULTI-HOST model: no single-chip or 8-chip AOT cell
    exists on purpose; the config is here so checkpoints convert and the
    tiny-geometry parity tests (test_hf_convert.py) pin the math."""
    return LlamaConfig(name="deepseek-v3", vocab_size=129280,
                       embed_dim=7168, n_layers=61, n_heads=128,
                       n_kv_heads=128, head_dim=128, mlp_dim=2048,
                       max_seq_len=163840, rope_theta=10_000.0,
                       rope_scaling={"rope_type": "yarn", "factor": 40.0,
                                     "beta_fast": 32, "beta_slow": 1,
                                     "mscale": 1.0, "mscale_all_dim": 1.0,
                                     "original_max_position_embeddings":
                                         4096},
                       norm_eps=1e-6,
                       mla_latent_dim=512, mla_rope_dim=64,
                       mla_q_lora_rank=1536,
                       n_experts=256, n_experts_per_tok=8,
                       n_shared_experts=1, router_norm_topk=True,
                       router_sigmoid_bias=True, router_n_group=8,
                       router_topk_group=4, routed_scaling_factor=2.5,
                       n_dense_prefix=3, dense_prefix_mlp_dim=18432)


def mla_8b() -> LlamaConfig:
    """8B-CLASS MLA benchmark geometry: llama3-8b's body (32L, 4096 wide,
    14336 MLP, 128k vocab) with V2-Lite MLA attention (latent 512 + rope
    64 at 32x128 heads) — the architecture A/B against llama3-8b at
    matched weight class (8.25B). ONE definition: bench.py --serve and
    tools/aot_check.py both consume this, so the AOT memory-fit proof
    can never drift from the model the staged serve step runs."""
    return LlamaConfig(name="mla-8b", vocab_size=128256, embed_dim=4096,
                       n_layers=32, n_heads=32, n_kv_heads=32,
                       head_dim=128, mla_latent_dim=512, mla_rope_dim=64,
                       mlp_dim=14336, max_seq_len=8192,
                       rope_theta=500_000.0)


def tiny_mla(**kw) -> LlamaConfig:
    """Tiny MLA config for tests/CPU smoke: dense MLP under latent attention."""
    kw.setdefault("name", "tiny-mla")
    kw.setdefault("n_heads", 4)
    kw.setdefault("n_kv_heads", 4)
    kw.setdefault("head_dim", 32)
    kw.setdefault("mla_latent_dim", 64)
    kw.setdefault("mla_rope_dim", 16)
    return dataclasses.replace(LlamaConfig(), **kw)


def qwen3_8b() -> LlamaConfig:
    """Qwen3-8B: Llama-shaped GQA decoder with per-head-dim RMSNorm on
    q/k before RoPE (the Gemma-3-style qk_norm flag, no biases)."""
    return LlamaConfig(name="qwen3-8b", vocab_size=151936, embed_dim=4096,
                       n_layers=36, n_heads=32, n_kv_heads=8, head_dim=128,
                       mlp_dim=12288, max_seq_len=32768,
                       rope_theta=1_000_000.0, norm_eps=1e-6, qk_norm=True)


def tiny_llama(**kw) -> LlamaConfig:
    return dataclasses.replace(LlamaConfig(), **kw)


def tiny_moe(**kw) -> LlamaConfig:
    kw.setdefault("name", "tiny-moe")
    kw.setdefault("n_experts", 4)
    kw.setdefault("n_experts_per_tok", 2)
    return dataclasses.replace(LlamaConfig(), **kw)


# -- params -------------------------------------------------------------------

def _layer_axes(cfg: LlamaConfig) -> dict:
    """Logical-axis dict for ONE stacked layer group (main or prefix)."""
    if cfg.is_mla:
        # latent axes stay replicated ("latent": None in LOGICAL_RULES):
        # every tensor-parallel shard reads the WHOLE latent cache — its
        # heads attend over all positions' latents — so only the per-head
        # dims (w_q / w_uk / w_uv outputs, w_o input) shard over tensor.
        if cfg.mla_q_lora_rank is not None:
            q_axes = {"w_qa": ("layer", "embed", "latent"),
                      "q_a_norm": ("layer", "norm"),
                      "w_qb": ("layer", "latent", "heads")}
        else:
            q_axes = {"wq": ("layer", "embed", "heads")}
        layer = {
            "attn_norm": ("layer", "norm"),
            **q_axes,
            "w_dkv": ("layer", "embed", "latent"),
            "c_norm": ("layer", "norm"),   # kv_a_layernorm, (r,) per layer
            "w_uk": ("layer", "latent", "heads"),
            "w_uv": ("layer", "latent", "heads"),
            "wo": ("layer", "heads", "embed"),
            "mlp_norm": ("layer", "norm"),
        }
    else:
        layer = {
            "attn_norm": ("layer", "norm"),
            "wq": ("layer", "embed", "heads"),
            "wk": ("layer", "embed", "kv_heads"),
            "wv": ("layer", "embed", "kv_heads"),
            "wo": ("layer", "heads", "embed"),
            "mlp_norm": ("layer", "norm"),
        }
    if cfg.post_norms:
        layer.update({"attn_post_norm": ("layer", "norm"),
                      "mlp_post_norm": ("layer", "norm")})
    if cfg.qk_norm:
        layer.update({"q_norm": ("layer", "norm"),
                      "k_norm": ("layer", "norm")})
    if cfg.qkv_bias:
        layer.update({"wq_b": ("layer", "heads"),
                      "wk_b": ("layer", "kv_heads"),
                      "wv_b": ("layer", "kv_heads")})
    if cfg.n_experts:
        layer.update({
            "router": ("layer", "embed", "expert"),
            "we_gate": ("layer", "expert", "embed", "mlp"),
            "we_up": ("layer", "expert", "embed", "mlp"),
            "we_down": ("layer", "expert", "mlp", "embed"),
        })
        if cfg.router_sigmoid_bias:
            layer.update({"router_bias": ("layer", "expert")})
        if cfg.n_shared_experts:
            layer.update({
                "ws_gate": ("layer", "embed", "mlp"),
                "ws_up": ("layer", "embed", "mlp"),
                "ws_down": ("layer", "mlp", "embed"),
            })
    else:
        layer.update({
            "w_gate": ("layer", "embed", "mlp"),
            "w_up": ("layer", "embed", "mlp"),
            "w_down": ("layer", "mlp", "embed"),
        })
    return layer


def param_logical_axes(cfg: LlamaConfig) -> Params:
    """Pytree (matching init_params) of logical-axis tuples."""
    layer = _layer_axes(cfg.main_cfg())
    tree: Params = {"tok_embed": ("vocab", "embed"),
                    "final_norm": ("norm",),
                    "layers": layer}
    if cfg.n_dense_prefix:
        tree["prefix_layers"] = _layer_axes(cfg.prefix_cfg())
    if not cfg.tie_embeddings:
        tree["lm_head"] = ("embed", "vocab")
    return tree


def _layer_shapes(cfg: LlamaConfig) -> dict:
    """Shape dict for ONE stacked layer group (main or prefix)."""
    e, hd = cfg.embed_dim, cfg.head_dim_
    if cfg.is_mla:
        r, dr = cfg.mla_latent_dim, cfg.mla_rope_dim
        qr = cfg.mla_q_lora_rank
        if qr is not None:
            q_shapes = {"w_qa": (cfg.n_layers, e, qr),
                        "q_a_norm": (cfg.n_layers, qr),
                        "w_qb": (cfg.n_layers, qr,
                                 cfg.n_heads * (hd + dr))}
        else:
            q_shapes = {"wq": (cfg.n_layers, e, cfg.n_heads * (hd + dr))}
        attn_shapes = {
            **q_shapes,
            "w_dkv": (cfg.n_layers, e, r + dr),
            "c_norm": (cfg.n_layers, r),
            "w_uk": (cfg.n_layers, r, cfg.n_heads * hd),
            "w_uv": (cfg.n_layers, r, cfg.n_heads * hd),
        }
    else:
        attn_shapes = {
            "wq": (cfg.n_layers, e, cfg.n_heads * hd),
            "wk": (cfg.n_layers, e, cfg.n_kv_heads * hd),
            "wv": (cfg.n_layers, e, cfg.n_kv_heads * hd),
        }
    layer = {
        "attn_norm": (cfg.n_layers, e),
        **attn_shapes,
        "wo": (cfg.n_layers, cfg.n_heads * hd, e),
        "mlp_norm": (cfg.n_layers, e),
    }
    if cfg.post_norms:
        layer.update({
            "attn_post_norm": (cfg.n_layers, e),
            "mlp_post_norm": (cfg.n_layers, e),
        })
    if cfg.qk_norm:
        layer.update({
            "q_norm": (cfg.n_layers, hd),
            "k_norm": (cfg.n_layers, hd),
        })
    if cfg.qkv_bias:
        layer.update({
            "wq_b": (cfg.n_layers, cfg.n_heads * hd),
            "wk_b": (cfg.n_layers, cfg.n_kv_heads * hd),
            "wv_b": (cfg.n_layers, cfg.n_kv_heads * hd),
        })
    if cfg.n_experts:
        layer.update({
            "router": (cfg.n_layers, e, cfg.n_experts),
            "we_gate": (cfg.n_layers, cfg.n_experts, e, cfg.mlp_dim),
            "we_up": (cfg.n_layers, cfg.n_experts, e, cfg.mlp_dim),
            "we_down": (cfg.n_layers, cfg.n_experts, cfg.mlp_dim, e),
        })
        if cfg.router_sigmoid_bias:
            layer.update({"router_bias": (cfg.n_layers, cfg.n_experts)})
        if cfg.n_shared_experts:
            sw = cfg.n_shared_experts * cfg.mlp_dim
            layer.update({
                "ws_gate": (cfg.n_layers, e, sw),
                "ws_up": (cfg.n_layers, e, sw),
                "ws_down": (cfg.n_layers, sw, e),
            })
    else:
        layer.update({
            "w_gate": (cfg.n_layers, e, cfg.mlp_dim),
            "w_up": (cfg.n_layers, e, cfg.mlp_dim),
            "w_down": (cfg.n_layers, cfg.mlp_dim, e),
        })
    return layer


def init_params(cfg: LlamaConfig, key: jax.Array,
                mesh: Optional[Mesh] = None) -> Params:
    """Initialize (optionally directly sharded onto ``mesh``)."""
    cfg.validate_mla()
    e, hd = cfg.embed_dim, cfg.head_dim_
    shapes: Params = {
        "tok_embed": (cfg.vocab_size, e),
        "final_norm": (e,),
        "layers": _layer_shapes(cfg.main_cfg()),
    }
    if cfg.n_dense_prefix:
        shapes["prefix_layers"] = _layer_shapes(cfg.prefix_cfg())
    if not cfg.tie_embeddings:
        shapes["lm_head"] = (e, cfg.vocab_size)

    leaves, treedef = jax.tree_util.tree_flatten(shapes,
                                                 is_leaf=lambda x: isinstance(x, tuple))
    keys = jax.random.split(key, len(leaves))

    def make(shape, k):
        if len(shape) <= 2 and shape[-1] == e:
            # norm weights: identity scale — 1, or 0 when applied as (1+w)
            # ((e,) final norm; (L, e) / (k_prefix, e) stacked layer norms)
            if len(shape) == 1 or shape[0] in (cfg.n_layers,
                                               cfg.n_dense_prefix,
                                               cfg.n_layers
                                               - cfg.n_dense_prefix):
                fill = 0.0 if cfg.norm_zero_centered else 1.0
                return jnp.full(shape, fill, cfg.param_dtype)
        scale = 0.02
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(cfg.param_dtype)

    params = jax.tree_util.tree_unflatten(
        treedef, [make(s, k) for s, k in zip(leaves, keys)])
    stacks = [params["layers"]] + ([params["prefix_layers"]]
                                   if cfg.n_dense_prefix else [])
    for lp in stacks:
        if cfg.qkv_bias:
            for name in ("wq_b", "wk_b", "wv_b"):
                lp[name] = jnp.zeros_like(lp[name])
        if cfg.qk_norm:  # identity init ((L, hd) misses make()'s rule)
            fill = 0.0 if cfg.norm_zero_centered else 1.0
            for name in ("q_norm", "k_norm"):
                lp[name] = jnp.full_like(lp[name], fill)
        if cfg.router_sigmoid_bias and "router_bias" in lp:
            lp["router_bias"] = jnp.zeros_like(lp["router_bias"])
        if cfg.is_mla:   # kv_a/q_a layernorms: identity init ((L, r) ditto)
            fill = 0.0 if cfg.norm_zero_centered else 1.0
            lp["c_norm"] = jnp.full_like(lp["c_norm"], fill)
            if cfg.mla_q_lora_rank is not None:
                lp["q_a_norm"] = jnp.full_like(lp["q_a_norm"], fill)
    if mesh is not None:
        axes = param_logical_axes(cfg)
        params = jax.tree_util.tree_map(
            lambda p, a: jax.device_put(p, logical_sharding(mesh, a)),
            params, axes)
    return params


# -- forward ------------------------------------------------------------------

def _constrain(x, mesh: Optional[Mesh], axes):
    return shard_logical(x, mesh, axes) if mesh is not None else x


def _rope_tables(cfg: LlamaConfig):
    """(global, local) RoPE tables. Local sublayers (windowed) rotate with
    rope_local_theta and NO position scaling (Gemma-3); without a local
    theta both kinds share the global table."""
    rope_dim = cfg.mla_rope_dim if cfg.is_mla else cfg.head_dim_
    g = rope_frequencies(rope_dim, cfg.max_seq_len, cfg.rope_theta,
                         cfg.rope_scaling)
    if cfg.rope_local_theta is None:
        return g, g
    loc = rope_frequencies(rope_dim, cfg.max_seq_len,
                           cfg.rope_local_theta, None)
    return g, loc


def _rope_for(tables, window: Optional[int]):
    return tables[1] if window is not None else tables[0]


def _group_layers(tree, p: int):
    """Reshape stacked layer leaves (L, ...) -> (L//p, p, ...) so a scan over
    layer GROUPS can give each sublayer a different STATIC attention window
    (Gemma-2 local/global interleave). p=1 returns the tree unchanged."""
    if p == 1:
        return tree

    def reshape(a):
        if a.shape[0] % p:
            raise ValueError(f"n_layers {a.shape[0]} not divisible by "
                             f"sliding_window_pattern {p}")
        return a.reshape((a.shape[0] // p, p) + a.shape[1:])

    return jax.tree_util.tree_map(reshape, tree)


def _sublayer(tree, j: int, p: int):
    """Select sublayer ``j`` of a group (identity when p=1)."""
    if p == 1:
        return tree
    return jax.tree_util.tree_map(lambda a: a[j], tree)


def _maybe_remat(fn, cfg: LlamaConfig):
    """Wraps a scan block with the configured rematerialization policy."""
    if not cfg.remat or cfg.remat_policy == "none":
        return fn
    if cfg.remat_policy == "dots":
        return jax.checkpoint(
            fn, policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
    if cfg.remat_policy != "full":
        raise ValueError(f"unknown remat_policy {cfg.remat_policy!r}")
    return jax.checkpoint(fn)


def _mm(h, w, dtype):
    """Matmul against a raw weight or a structured dict leaf:
    - int8 weight-only quant {"q8", "scale"} (models/quant.py): the dequant
      multiply sits in the matmul epilogue where XLA fuses it — HBM reads
      stay int8.
    - int4 weight-only quant {"q4", "scale"}: two weights per uint8 byte,
      group-wise scales along the contraction axis; unpack + dequant fuse
      into the matmul operand load, so HBM reads stay at a quarter of bf16.
    - LoRA adapter {"w", "lora_a", "lora_b", "scale"} (models/lora.py): the
      base weight is stop_gradient'd so backward exists only for A/B."""
    if isinstance(w, dict):
        if "lora_a" in w:
            base = h @ jax.lax.stop_gradient(w["w"]).astype(dtype)
            delta = (h @ w["lora_a"].astype(dtype)) @ w["lora_b"].astype(dtype)
            return base + delta * w["scale"].astype(dtype)
        if "q4" in w:
            return _mm_int4(h, w, dtype)
        return (h @ w["q8"].astype(dtype)) * w["scale"].astype(dtype)
    return h @ w.astype(dtype)


# Trace-time mesh for the int4 kernel: _mm's signature stays mesh-free
# across its ~20 call sites, and the model's public entry points (each
# decorated with _with_int4_mesh) publish self.mesh here instead. Safe
# because it is read during TRACING only — the jitted program bakes the
# mesh in, exactly like the closure-captured mesh everywhere else.
_INT4_MESH: "contextvars.ContextVar[Optional[Mesh]]" = \
    contextvars.ContextVar("int4_mesh", default=None)


def _with_int4_mesh(fn):
    @functools.wraps(fn)
    def wrapped(self, *a, **k):
        tok = _INT4_MESH.set(self.mesh)
        try:
            return fn(self, *a, **k)
        finally:
            _INT4_MESH.reset(tok)
    return wrapped


def _mm_int4(h, w, dtype):
    """h (..., in) @ int4-packed weight -> (..., out).

    q4 is (in/2, out) uint8 (low nibble = in-element 2i, high = 2i+1),
    scale (g, 1, out) with g groups along the contraction axis
    (quant.py _quantize_leaf_int4). Two design rules keep HBM reads at a
    quarter of bf16 (the point of int4), both learned from the AOT cost
    model refuting a first draft that hit 3x the int8 bytes:

    - NO nibble interleave: a stack+reshape to restore in-element order is
      a permute XLA materializes (the dequantized bf16 weights land in
      HBM). Instead the low/high nibble planes each stay contiguous and
      contract against h's even/odd strides — two half-depth matmuls whose
      operand chains (byte load -> mask/shift -> cast) fuse.
    - scales apply to the small per-group PARTIALS after the matmul, not
      to the weights before it, so the only op on the big tensor is the
      cast. Even/odd elements of one group share its scale (group size is
      even), so the group axis survives the split intact.

    Even so, XLA materializes the cast nibble planes (AOT-measured 9.0GB
    accessed vs int8's 6.3GB at the 8B decode) — on TPU the matmul runs as
    a Pallas kernel (ops/int4_matmul.py) that unpacks in VMEM; this module
    keeps only the XLA fallback for CPU/interpret paths."""
    from ..ops.int4_matmul import int4_matmul, int4_matmul_sharded
    mesh = _INT4_MESH.get()
    if mesh is not None and mesh.size > 1:
        # ANY multi-device mesh goes through the shard_map wrapper, not
        # just tensor>1: a bare pallas_call in a GSPMD program over a
        # multi-device mesh (e.g. expert-parallel with tensor=1) fails
        # with "Mosaic kernels cannot be automatically partitioned" —
        # shard_map makes the partitioning manual either way, and at
        # tensor=1 its specs degenerate to full-array (replicated) blocks
        return int4_matmul_sharded(h.astype(dtype), w["q4"], w["scale"],
                                   mesh, axis=AXES.TENSOR)
    return int4_matmul(h.astype(dtype), w["q4"], w["scale"])


def _norm_w(w, cfg: LlamaConfig):
    """Gemma stores RMSNorm weights zero-centered and applies (1 + w)."""
    return w + 1 if cfg.norm_zero_centered else w


def _activation(cfg: LlamaConfig):
    if cfg.mlp_activation == "silu":
        return jax.nn.silu
    if cfg.mlp_activation == "gelu_tanh":
        return functools.partial(jax.nn.gelu, approximate=True)
    raise ValueError(f"unknown mlp_activation {cfg.mlp_activation!r}")


def _embed(params: Params, tokens: jax.Array, cfg: LlamaConfig,
           mesh: Optional[Mesh] = None) -> jax.Array:
    table = params["tok_embed"].astype(cfg.dtype)
    if mesh is not None and mesh.shape.get(AXES.TENSOR, 1) > 1:
        # The table's vocab dim is tensor-sharded (sharding.py rules); a
        # gather from it forces the SPMD partitioner into involuntary full
        # rematerialization (replicate-then-reshard, spmd_partitioner.cc
        # warning seen in MULTICHIP_r01).  A one-hot contraction instead
        # rides the MXU and turns the vocab-sharded axis into a clean psum
        # over `tensor` — XLA fuses the iota/compare into the matmul loop.
        tokens = _constrain(tokens, mesh, ("batch", "seq"))
        one_hot = jax.nn.one_hot(tokens, cfg.vocab_size, dtype=cfg.dtype)
        x = one_hot @ table
    else:
        x = table[tokens]
    if cfg.embed_scale:
        x = x * jnp.asarray(cfg.embed_dim ** 0.5, cfg.dtype)
    return x


def _head_logits(x: jax.Array, params: Params, cfg: LlamaConfig) -> jax.Array:
    if cfg.tie_embeddings:
        logits = x @ params["tok_embed"].T.astype(cfg.dtype)
    else:
        logits = _mm(x, params["lm_head"], cfg.dtype)
    if cfg.logit_softcap:
        cap = jnp.asarray(cfg.logit_softcap, logits.dtype)
        logits = jnp.tanh(logits / cap) * cap
    return logits


def _qkv(h, lp, cfg: LlamaConfig, b: int, s: int):
    """q/k/v projections (+ Qwen-style bias when configured), head-split."""
    hd = cfg.head_dim_
    q = _mm(h, lp["wq"], cfg.dtype)
    k = _mm(h, lp["wk"], cfg.dtype)
    v = _mm(h, lp["wv"], cfg.dtype)
    if cfg.qkv_bias:
        q = q + lp["wq_b"].astype(cfg.dtype)
        k = k + lp["wk_b"].astype(cfg.dtype)
        v = v + lp["wv_b"].astype(cfg.dtype)
    return (q.reshape(b, s, cfg.n_heads, hd),
            k.reshape(b, s, cfg.n_kv_heads, hd),
            v.reshape(b, s, cfg.n_kv_heads, hd))


def yarn_mscale_sq(cfg: LlamaConfig) -> float:
    """YaRN's other half: with rope_scaling mscale_all_dim, the attention
    SOFTMAX scale multiplies by yarn_get_mscale(factor, mscale_all_dim)^2
    (DeepseekV3Attention and DeepSeek's original remote code both apply
    it; transformers' DeepseekV2 class omits it — we follow the original
    semantics real checkpoints were trained with). 1.0 otherwise."""
    sc = cfg.rope_scaling or {}
    rt = sc.get("rope_type", sc.get("type"))
    ms_all = sc.get("mscale_all_dim")
    f = float(sc.get("factor", 1.0))
    if rt != "yarn" or not ms_all or f <= 1:
        return 1.0
    import math
    m = 0.1 * float(ms_all) * math.log(f) + 1.0
    return m * m


def _mla_project(h, lp, cfg: LlamaConfig, cos, sin, positions, b, s):
    """MLA projections: q_nope (B,S,H,dh), q_rope (B,S,H,dr) rotated,
    latent c (B,S,r) NORMED, shared rope key kr (B,S,dr) rotated. One
    w_dkv matmul yields both cache sections (DeepSeek-V2 decoupled RoPE).

    ``c_norm`` is DeepSeek's kv_a_layernorm: RMSNorm on the compressed
    latent before the up-projections (the rope key bypasses it). The
    NORMED latent is what gets cached — per-token and deterministic, so
    caching post-norm is equivalent to norming on every read, and the
    absorbed decode's q_lat . c stays a plain dot.

    ``mla_q_lora_rank`` (V2-full/V3): q goes through its own low-rank
    bottleneck — q = q_a_layernorm(h @ wq_a) @ wq_b — instead of wq."""
    hd, dr, r = cfg.head_dim_, cfg.mla_rope_dim, cfg.mla_latent_dim
    if cfg.mla_q_lora_rank is not None:
        qa = _mm(h, lp["w_qa"], cfg.dtype)
        qa = rms_norm(qa, _norm_w(lp["q_a_norm"], cfg), cfg.norm_eps)
        q = _mm(qa, lp["w_qb"], cfg.dtype).reshape(b, s, cfg.n_heads,
                                                   hd + dr)
    else:
        q = _mm(h, lp["wq"], cfg.dtype).reshape(b, s, cfg.n_heads, hd + dr)
    ckr = _mm(h, lp["w_dkv"], cfg.dtype)
    c, kr = ckr[..., :r], ckr[..., r:]
    c = rms_norm(c, _norm_w(lp["c_norm"], cfg), cfg.norm_eps)
    q_nope, q_rope = q[..., :hd], q[..., hd:]
    q_rope = apply_rope(q_rope, cos, sin, positions)
    kr = apply_rope(kr[:, :, None, :], cos, sin, positions)[:, :, 0]
    return q_nope, q_rope, c, kr


def _mla_attention_block(x, lp, cfg: LlamaConfig, cos, sin, mesh,
                         positions=None, return_kv: bool = False):
    """Direct-form MLA for training/prefill (compute-bound phases):
    materialize per-head K/V from the latent, then concatenate the shared
    rotated key onto each head's K so the two-part MLA score
    (q_nope . k_nope + q_rope . kr) is a SINGLE dot product — the existing
    flash/ring kernels serve unchanged. V is zero-padded to the qk width
    (its tail contributes nothing; sliced off after). Decode uses the
    absorbed form (_verify_step_mla) — that is where the latent cache's
    bandwidth win lives."""
    b, s, e = x.shape
    hd, dr = cfg.head_dim_, cfg.mla_rope_dim
    hn = cfg.n_heads
    h = rms_norm(x, _norm_w(lp["attn_norm"], cfg), cfg.norm_eps)
    q_nope, q_rope, c, kr = _mla_project(h, lp, cfg, cos, sin, positions,
                                         b, s)
    k_nope = _mm(c, lp["w_uk"], cfg.dtype).reshape(b, s, hn, hd)
    v = _mm(c, lp["w_uv"], cfg.dtype).reshape(b, s, hn, hd)
    q_full = jnp.concatenate([q_nope, q_rope], axis=-1)
    k_full = jnp.concatenate(
        [k_nope, jnp.broadcast_to(kr[:, :, None, :], (b, s, hn, dr))],
        axis=-1)
    v_full = jnp.concatenate(
        [v, jnp.zeros((b, s, hn, dr), v.dtype)], axis=-1)
    q_full = _constrain(q_full, mesh, ("batch", "seq", "act_heads",
                                       "head_dim"))
    qt, kt, vt = (t.transpose(0, 2, 1, 3) for t in (q_full, k_full, v_full))
    scale = (hd + dr) ** -0.5 * yarn_mscale_sq(cfg)
    if mesh is not None and mesh.shape.get(AXES.SEQ, 1) > 1:
        o = ring_attention(qt, kt, vt, mesh, causal=True, sm_scale=scale,
                           use_flash=cfg.ring_flash)
    else:
        o = flash_attention(qt, kt, vt, causal=True, sm_scale=scale)
    o = o.transpose(0, 2, 1, 3)[..., :hd].reshape(b, s, hn * hd)
    o = _mm(o, lp["wo"], cfg.dtype)
    if cfg.post_norms:
        o = rms_norm(o, _norm_w(lp["attn_post_norm"], cfg), cfg.norm_eps)
    if return_kv:
        return x + o, c, kr  # the latent cache content (B,S,r)/(B,S,dr)
    return x + o


def _attention_block(x, lp, cfg: LlamaConfig, cos, sin, mesh, positions=None,
                     window: Optional[int] = None, return_kv: bool = False,
                     ad: Optional[dict] = None,
                     ad_ids: Optional[jax.Array] = None):
    if cfg.is_mla:
        if ad:
            raise ValueError("multi-LoRA adapters do not target MLA "
                             "projections (wq/w_dkv/w_uk/w_uv layout "
                             "differs); serve MLA models without adapters")
        return _mla_attention_block(x, lp, cfg, cos, sin, mesh, positions,
                                    return_kv)
    b, s, e = x.shape
    hd = cfg.head_dim_
    h = rms_norm(x, _norm_w(lp["attn_norm"], cfg), cfg.norm_eps)
    q, k, v = _qkv(h, lp, cfg, b, s)
    q, k, v = _ml_qkv_deltas(h, q, k, v, ad, ad_ids)  # multi-LoRA serving
    if cfg.qk_norm:  # Gemma-3: per-head RMSNorm on q/k, before RoPE
        q = rms_norm(q, _norm_w(lp["q_norm"], cfg), cfg.norm_eps)
        k = rms_norm(k, _norm_w(lp["k_norm"], cfg), cfg.norm_eps)
    q = apply_rope(q, cos, sin, positions)
    k = apply_rope(k, cos, sin, positions)
    q = _constrain(q, mesh, ("batch", "seq", "act_heads", "head_dim"))
    # (B,S,H,D) -> (B,H,S,D)
    qt, kt, vt = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
    if mesh is not None and mesh.shape.get(AXES.SEQ, 1) > 1:
        # softcap and sliding window ride the ring (band-masked chunks with
        # out-of-band skip), so Gemma-2/3 interleaves get sequence
        # parallelism: global sublayers ring the full context, local ones
        # only pay for in-window chunks
        o = ring_attention(qt, kt, vt, mesh, causal=True,
                           sm_scale=cfg.sm_scale,
                           logit_soft_cap=cfg.attn_logit_softcap,
                           sliding_window=window,
                           use_flash=cfg.ring_flash)
    else:
        o = flash_attention(qt, kt, vt, causal=True, sm_scale=cfg.sm_scale,
                            sliding_window=window,
                            logit_soft_cap=cfg.attn_logit_softcap)
    o = o.transpose(0, 2, 1, 3).reshape(b, s, cfg.n_heads * hd)
    o_in = o
    o = _mm(o, lp["wo"], cfg.dtype)
    if ad and "wo" in ad:
        o = o + _ml_delta(o_in, ad["wo"], ad_ids)
    if cfg.post_norms:
        o = rms_norm(o, _norm_w(lp["attn_post_norm"], cfg), cfg.norm_eps)
    if return_kv:
        return x + o, k, v  # (B,S,Hkv,D) rope'd — the prefill cache layout
    return x + o


def _mlp_block(x, lp, cfg: LlamaConfig, mesh, train: bool = True,
               ad: Optional[dict] = None,
               ad_ids: Optional[jax.Array] = None):
    """Dense SwiGLU/GeGLU MLP, or sparse MoE when cfg.n_experts > 0.
    Returns (residual output, scaled router aux loss — 0.0 for dense).
    ``train=False`` (serving prefill/decode) routes with a no-drop capacity
    (factor = n_experts/k ⇒ cap = G, the most tokens any one expert can get
    since a token's top-k picks are distinct): capacity drops are a
    training-throughput trade, never acceptable token corruption at
    inference — reference Mixtral never drops."""
    h = rms_norm(x, _norm_w(lp["mlp_norm"], cfg), cfg.norm_eps)
    if cfg.n_experts:
        y, aux, z = moe_mlp(
            h, lp["router"], lp["we_gate"], lp["we_up"], lp["we_down"],
            n_experts_per_tok=cfg.n_experts_per_tok,
            capacity_factor=(cfg.capacity_factor if train
                             else cfg.n_experts / cfg.n_experts_per_tok),
            activation=_activation(cfg), dtype=cfg.dtype,
            constrain=(lambda t, axes: _constrain(t, mesh, axes)),
            norm_topk=cfg.router_norm_topk,
            router_bias=(lp["router_bias"] if cfg.router_sigmoid_bias
                         else None),
            router_n_group=cfg.router_n_group,
            router_topk_group=cfg.router_topk_group,
            routed_scaling=cfg.routed_scaling_factor,
            # inference threads the mesh so an expert axis (or int4 expert
            # weights, opaque to GSPMD) runs the expert FFN under shard_map;
            # training keeps the GSPMD/constraint path (moe_mlp docstring)
            mesh=None if train else mesh)
        aux = cfg.router_aux_coef * aux + cfg.router_z_coef * z
        if cfg.n_shared_experts:
            # DeepSeek-MoE shared experts: an always-on dense MLP (width
            # n_shared * mlp_dim) added to the routed output; the router
            # never sees it, so no aux-loss contribution
            gate_s = _mm(h, lp["ws_gate"], cfg.dtype)
            up_s = _mm(h, lp["ws_up"], cfg.dtype)
            act_s = _constrain(_activation(cfg)(gate_s) * up_s, mesh,
                               ("batch", "seq", "act_mlp"))
            y = y + _mm(act_s, lp["ws_down"], cfg.dtype)
    else:
        gate = _mm(h, lp["w_gate"], cfg.dtype)
        up = _mm(h, lp["w_up"], cfg.dtype)
        if ad:
            if "w_gate" in ad:
                gate = gate + _ml_delta(h, ad["w_gate"], ad_ids)
            if "w_up" in ad:
                up = up + _ml_delta(h, ad["w_up"], ad_ids)
        act = _constrain(_activation(cfg)(gate) * up, mesh,
                         ("batch", "seq", "act_mlp"))
        y = _mm(act, lp["w_down"], cfg.dtype)
        if ad and "w_down" in ad:
            y = y + _ml_delta(act, ad["w_down"], ad_ids)
        aux = jnp.float32(0.0)
    if cfg.post_norms:
        y = rms_norm(y, _norm_w(lp["mlp_post_norm"], cfg), cfg.norm_eps)
    return x + y, aux


def _ml_qkv_deltas(h, q, k, v, ad: Optional[dict], ids):
    """Apply per-row adapter deltas to the q/k/v projections (one helper so
    the prefill and decode kernels cannot drift)."""
    if ad:
        if "wq" in ad:
            q = q + _ml_delta(h, ad["wq"], ids).reshape(q.shape)
        if "wk" in ad:
            k = k + _ml_delta(h, ad["wk"], ids).reshape(k.shape)
        if "wv" in ad:
            v = v + _ml_delta(h, ad["wv"], ids).reshape(v.shape)
    return q, k, v


def _ml_delta(x: jax.Array, ad: dict, ids: jax.Array) -> jax.Array:
    """Batched multi-LoRA delta with PER-ROW adapter selection (multi-tenant
    serving: requests in the same decode batch use different adapters).
    x (B, S, in); ad {"a": (N, in, r), "b": (N, r, out), "scale": (N,)};
    ids (B,) int32 into the adapter axis. Slot 0 is all-zeros = base model,
    so "no adapter" needs no conditional. The gathers move only
    O(B * r * (in + out)) bytes — tiny next to the base matmul."""
    a_sel = ad["a"][ids].astype(x.dtype)               # (B, in, r)
    b_sel = ad["b"][ids].astype(x.dtype)               # (B, r, out)
    d = jnp.einsum("bsi,bir->bsr", x, a_sel)
    d = jnp.einsum("bsr,bro->bso", d, b_sel)
    return d * ad["scale"][ids].astype(x.dtype)[:, None, None]


def _kv_quant(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Symmetric int8 rows over the last (head_dim) axis: (..., d) ->
    (int8 (..., d), f32 scale (...,))."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.round(x.astype(jnp.float32) / scale[..., None])
    return jnp.clip(q, -127, 127).astype(jnp.int8), scale


def _kv_dequant(q: jax.Array, scale: jax.Array, dtype=jnp.float32) -> jax.Array:
    return q.astype(dtype) * scale[..., None].astype(dtype)


class LlamaModel:
    """Functional model: forward(params, tokens) and decode-step methods."""

    def __init__(self, cfg: LlamaConfig, mesh: Optional[Mesh] = None):
        self.cfg = cfg
        self.mesh = mesh

    @_with_int4_mesh
    def forward(self, params: Params, tokens: jax.Array,
                positions: Optional[jax.Array] = None,
                with_aux: bool = False, return_hidden: bool = False):
        """tokens (B, S) int32 -> logits (B, S, V).
        ``with_aux=True`` additionally returns the summed (pre-scaled) router
        aux loss — nonzero only for MoE configs; add it to the train loss.
        ``return_hidden=True`` stops BEFORE the LM head and returns the
        final-norm hidden states (B, S, E) instead of logits — the input the
        chunked fused cross-entropy (ops/fused_ce.py) consumes so the (B, S,
        V) logits tensor never materializes."""
        cfg, mesh = self.cfg, self.mesh
        ropes = _rope_tables(cfg)
        x = _embed(params, tokens, cfg, mesh)
        x = _constrain(x, mesh, ("batch", "seq", "act_embed"))

        pat = cfg.sliding_window_pattern
        windows = cfg.layer_windows()

        def make_group_block(mesh_, positions_, cfg_=cfg, windows_=windows,
                             pat_=pat):
            """Scan body over one layer GROUP: each sublayer gets its
            STATIC window + rope table (Gemma-2/3 local/global interleave;
            pat=1 is the degenerate single-sublayer group). Shared by the
            plain and pipelined paths (pipeline: mesh_=None, mesh-free) and
            by the dense-prefix phase (cfg_=prefix_cfg: dense MLP, same
            attention)."""
            def block(carry, lp_group):
                y = carry
                aux = jnp.float32(0.0)
                for j, win in enumerate(windows_):
                    lp = _sublayer(lp_group, j, pat_)
                    cs, sn = _rope_for(ropes, win)
                    y = _attention_block(y, lp, cfg_, cs, sn, mesh_,
                                         positions_, window=win)
                    y, a = _mlp_block(y, lp, cfg_, mesh_)
                    y = _constrain(y, mesh_, ("batch", "seq", "act_embed"))
                    aux = aux + a
                return y, aux
            return block

        if cfg.n_dense_prefix:
            # dense-prefix phase (DeepSeek first_k_dense_replace): same
            # attention, dense MLP, scanned BEFORE the main stack
            if pipeline_stages(mesh) > 1:
                raise ValueError("n_dense_prefix does not compose with "
                                 "pipeline parallelism (heterogeneous "
                                 "stages)")
            pbody = _maybe_remat(
                make_group_block(mesh, positions, cfg_=cfg.prefix_cfg(),
                                 windows_=(None,), pat_=1), cfg)
            x, aux_prefix = jax.lax.scan(pbody, x, params["prefix_layers"])
        else:
            aux_prefix = jnp.zeros((0,), jnp.float32)

        n_stages = pipeline_stages(mesh)
        if n_stages > 1:
            # GPipe over the stage axis (parallel/pipeline.py). Blocks run
            # mesh-free inside the vmapped stage: GSPMD shardings never change
            # values, and XLA still propagates the tensor-axis layout from the
            # param shardings; ring attention (a manual shard_map) is the one
            # thing that can't nest here, so seq stays XLA-managed.
            if positions is not None:
                raise ValueError("pipeline forward does not take positions")
            if mesh.shape.get(AXES.SEQ, 1) > 1:
                raise ValueError(
                    "stage>1 does not compose with seq>1: the pipeline stage "
                    "runs mesh-free, so ring attention never engages and the "
                    "seq-axis devices would sit idle — use fsdp/tensor/data "
                    "for the remaining devices instead")
            if cfg.n_layers % n_stages:
                # fire the accurate error before the pattern guard below
                # could report a fabricated layers-per-stage count
                raise ValueError(f"n_layers={cfg.n_layers} not divisible by "
                                 f"{n_stages} stages")
            per_stage = cfg.n_layers // n_stages
            if per_stage % pat:
                # a local/global group straddling a stage boundary would put
                # its sublayers on different devices mid-scan
                raise ValueError(
                    f"{per_stage} layers/stage not divisible by "
                    f"sliding_window_pattern {pat}: each stage must hold "
                    "whole local/global groups — pick n_stages so "
                    "n_layers/n_stages is a multiple of the pattern")

            # the ONE grouped-scan body (below) with mesh=None: stage blocks
            # run mesh-free, and _constrain(_, None, _) is the identity —
            # a single closure keeps the pipelined forward definitionally
            # equal to the plain forward it is tested against
            sbody = _maybe_remat(make_group_block(None, None), cfg)

            def stage_fn(stage_layers, x_mb):
                y, auxes = jax.lax.scan(sbody, x_mb,
                                        _group_layers(stage_layers, pat))
                return y, jnp.sum(auxes)

            x, aux_total = pipeline_spmd(
                params["layers"], x, stage_fn, mesh=mesh,
                n_microbatches=cfg.pipeline_microbatches)
            aux_layers = aux_total[None]
        else:
            body = _maybe_remat(make_group_block(mesh, positions), cfg)
            x, aux_layers = jax.lax.scan(body, x,
                                         _group_layers(params["layers"], pat))
        x = rms_norm(x, _norm_w(params["final_norm"], cfg), cfg.norm_eps)
        if return_hidden:
            if with_aux:
                return x, jnp.sum(aux_layers) + jnp.sum(aux_prefix)
            return x
        logits = _head_logits(x, params, cfg)
        logits = _constrain(logits, mesh, ("batch", "seq", "act_vocab"))
        if with_aux:
            return logits, jnp.sum(aux_layers) + jnp.sum(aux_prefix)
        return logits

    def __call__(self, params, tokens, positions=None):
        return self.forward(params, tokens, positions)

    # -- decode (serving) ------------------------------------------------------

    def init_cache(self, batch: int, max_len: Optional[int] = None,
                   quantize: bool = False) -> Params:
        """KV cache with PER-SLOT write indices — the decode batch is a set of
        independent in-flight requests (continuous batching), not one sequence.

        ``quantize=True`` stores K/V as int8 with per-(position, kv-head)
        f32 scales ("k_scale"/"v_scale"): decode is HBM-bandwidth-bound on
        cache reads, so int8 halves the traffic AND doubles how many slots
        fit; dequantization happens in-register after the load."""
        cfg = self.cfg
        max_len = max_len or cfg.max_seq_len
        return self._empty_cache(batch, max_len, quantize)

    def _empty_cache(self, batch: int, length: int, quantize: bool) -> Params:
        cfg = self.cfg
        dt = jnp.int8 if quantize else cfg.dtype
        if cfg.is_mla:
            # latent cache: (r + dr) per position instead of 2*h*d — the
            # architecture-level answer to decode HBM traffic (int8 on top
            # halves it again; the two compose like k/v int8 does).
            # Dense-prefix layers get their OWN sections (c_pre/kr_pre):
            # slicing one (L, ...) array per step would force a full-cache
            # concat on the decode hot path and break donation aliasing
            # (AOT-measured: +233MB temps, -34% roofline).
            r, dr = cfg.mla_latent_dim, cfg.mla_rope_dim
            kpre = cfg.n_dense_prefix
            lm = cfg.n_layers - kpre
            cache = {"c": jnp.zeros((lm, batch, length, r), dt),
                     "kr": jnp.zeros((lm, batch, length, dr), dt),
                     "index": jnp.zeros((batch,), jnp.int32)}
            if quantize:
                cache["c_scale"] = jnp.zeros((lm, batch, length),
                                             jnp.float32)
                cache["kr_scale"] = jnp.zeros((lm, batch, length),
                                              jnp.float32)
            if kpre:
                cache["c_pre"] = jnp.zeros((kpre, batch, length, r), dt)
                cache["kr_pre"] = jnp.zeros((kpre, batch, length, dr), dt)
                if quantize:
                    cache["c_pre_scale"] = jnp.zeros((kpre, batch, length),
                                                     jnp.float32)
                    cache["kr_pre_scale"] = jnp.zeros((kpre, batch, length),
                                                      jnp.float32)
            return cache
        shape = (cfg.n_layers, batch, length, cfg.n_kv_heads, cfg.head_dim_)
        cache = {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt),
                 "index": jnp.zeros((batch,), jnp.int32)}
        if quantize:
            cache["k_scale"] = jnp.zeros(shape[:-1], jnp.float32)
            cache["v_scale"] = jnp.zeros(shape[:-1], jnp.float32)
        return cache

    def init_ring_cache(self, batch: int, ring_len: int,
                        quantize: bool = False) -> Params:
        """RING KV cache for uniformly-windowed models (Mistral): physical
        size ``ring_len`` regardless of logical sequence length — position p
        lives in ring slot p % ring_len, and ``abs_pos`` (B, R) records which
        absolute position each slot currently holds (-1 = empty). Attention
        masks on abs_pos, so visibility is exact under chunked prefill and
        speculative rejections alike. Memory: O(W) per slot instead of
        O(cache_len) — a 32k-budget Mistral slot shrinks ~7x.

        Caller contract (the serving engine honors it): ring_len must be
        >= window + the largest number of tokens any single prefill/verify
        call writes, so a call can never overwrite a slot still inside some
        query's window."""
        cfg = self.cfg
        if cfg.sliding_window is None or cfg.sliding_window_pattern != 1:
            raise ValueError("ring cache requires a uniform sliding_window "
                             "(pattern 1); global-attention layers need the "
                             "full history")
        if ring_len <= cfg.sliding_window:
            raise ValueError(f"ring_len {ring_len} must exceed the window "
                             f"{cfg.sliding_window} (write slack)")
        cache = self._empty_cache(batch, ring_len, quantize)
        cache["abs_pos"] = jnp.full((batch, ring_len), -1, jnp.int32)
        return cache

    def init_mixed_cache(self, batch: int, max_len: int,
                         ring_len: int, quantize: bool = False) -> Params:
        """Split cache for local/global interleave models (Gemma-2/3):
        LOCAL (windowed) sublayers get a ring of ``ring_len`` slots (they
        can never attend further back than the window), GLOBAL sublayers
        keep the full ``max_len``. For gemma3-12b (5 local : 1 global,
        W=1024) this cuts cache memory ~6x at long contexts. Layout:
        "k_l"/"v_l" (n_local, B, R, h, d) in LAYER-GROUP ORDER (group g's
        local sublayers are rows g*(p-1)..), "k_g"/"v_g" (n_global, B,
        max_len, h, d); one shared "abs_pos" ring ownership map (every
        local layer writes the same slots). Same write-slack contract as
        init_ring_cache.

        ``quantize=True`` stores every section int8 with per-(position,
        kv-head) f32 scales ("k_l_scale"/"v_l_scale"/"k_g_scale"/
        "v_g_scale") — the ring's O(W) win and int8's 2x read-traffic win
        compose, they shrink different axes."""
        cfg = self.cfg
        p = cfg.sliding_window_pattern
        if cfg.sliding_window is None or p <= 1:
            raise ValueError("mixed cache requires a windowed interleave "
                             "(sliding_window set and pattern > 1); use "
                             "init_ring_cache/init_cache instead")
        if ring_len <= cfg.sliding_window:
            raise ValueError(f"ring_len {ring_len} must exceed the window "
                             f"{cfg.sliding_window} (write slack)")
        if cfg.n_layers % p:
            raise ValueError(f"n_layers {cfg.n_layers} not divisible by "
                             f"pattern {p}")
        n_groups = cfg.n_layers // p
        n_local = n_groups * (p - 1)
        h, d = cfg.n_kv_heads, cfg.head_dim_
        dt = jnp.int8 if quantize else cfg.dtype
        cache = {
            "k_l": jnp.zeros((n_local, batch, ring_len, h, d), dt),
            "v_l": jnp.zeros((n_local, batch, ring_len, h, d), dt),
            "k_g": jnp.zeros((n_groups, batch, max_len, h, d), dt),
            "v_g": jnp.zeros((n_groups, batch, max_len, h, d), dt),
            "index": jnp.zeros((batch,), jnp.int32),
            "abs_pos": jnp.full((batch, ring_len), -1, jnp.int32),
        }
        if quantize:
            cache["k_l_scale"] = jnp.zeros((n_local, batch, ring_len, h),
                                           jnp.float32)
            cache["v_l_scale"] = jnp.zeros((n_local, batch, ring_len, h),
                                           jnp.float32)
            cache["k_g_scale"] = jnp.zeros((n_groups, batch, max_len, h),
                                           jnp.float32)
            cache["v_g_scale"] = jnp.zeros((n_groups, batch, max_len, h),
                                           jnp.float32)
        return cache

    @_with_int4_mesh
    def prefill(self, params: Params, tokens: jax.Array, cache: Params,
                true_length: Optional[jax.Array] = None,
                adapters: Optional[dict] = None,
                adapter_ids: Optional[jax.Array] = None
                ) -> tuple[jax.Array, Params]:
        """Run the prompt through, filling the cache. Returns (last_logits, cache).

        ``true_length`` (B,) supports PADDED prompts (bucketed to a few fixed
        shapes so serving admission never recompiles): logits are taken at each
        row's last real token and the cache index starts there. Padded K/V
        positions are never attended — decode overwrites position i exactly when
        its index reaches i, before the mask exposes it."""
        cfg = self.cfg
        b, s = tokens.shape
        if true_length is None:
            true_length = jnp.full((b,), s, jnp.int32)
        ropes = _rope_tables(cfg)
        x = _embed(params, tokens, cfg, self.mesh)

        # one scan over layer groups that also collects the K/V it computes
        pat = cfg.sliding_window_pattern
        windows = cfg.layer_windows()

        def block(carry, inputs):
            y = carry
            lp_group = inputs["lp"]
            ad_group = inputs.get("ad")
            ks, vs = [], []
            for j, win in enumerate(windows):
                lp = _sublayer(lp_group, j, pat)
                adj = (_sublayer(ad_group, j, pat)
                       if ad_group is not None else None)
                cs, sn = _rope_for(ropes, win)
                y, k, v = _attention_block(y, lp, cfg, cs, sn, None,
                                           window=win, return_kv=True,
                                           ad=adj, ad_ids=adapter_ids)
                y, _ = _mlp_block(y, lp, cfg, self.mesh, train=False,
                                  ad=adj, ad_ids=adapter_ids)
                ks.append(k)
                vs.append(v)
            if pat > 1:
                return y, (jnp.stack(ks), jnp.stack(vs))
            return y, (ks[0], vs[0])

        prefix_kv = None
        if cfg.n_dense_prefix:  # MLA-only (validate_mla): collect c/kr
            pcfg = cfg.prefix_cfg()

            def pblock(carry, lp):
                y = carry
                cs, sn = _rope_for(ropes, None)
                y, c, kr = _attention_block(y, lp, pcfg, cs, sn, None,
                                            return_kv=True)
                y, _ = _mlp_block(y, lp, pcfg, self.mesh, train=False)
                return y, (c, kr)

            x, prefix_kv = jax.lax.scan(pblock, x, params["prefix_layers"])
        xs = {"lp": _group_layers(params["layers"], pat)}
        if adapters:
            xs["ad"] = _group_layers(adapters, pat)
        x, (k_all, v_all) = jax.lax.scan(block, x, xs)
        if pat > 1:  # (L//p, p, B, S, h, d) -> (L, B, S, h, d)
            k_all = k_all.reshape((cfg.n_layers,) + k_all.shape[2:])
            v_all = v_all.reshape((cfg.n_layers,) + v_all.shape[2:])
        x = rms_norm(x, _norm_w(params["final_norm"], cfg), cfg.norm_eps)
        last = x[jnp.arange(b), true_length - 1]  # (B, E): each row's last real token
        logits = _head_logits(last, params, cfg)
        if cfg.is_mla:  # k_all/v_all are the latent sections c/kr here
            max_len = cache["c"].shape[2]
            if s > max_len:
                raise ValueError(f"prompt length {s} exceeds cache length "
                                 f"{max_len}")
            pad4 = [(0, 0), (0, 0), (0, max_len - s), (0, 0)]
            quantize = "c_scale" in cache
            new_cache = {"index": true_length.astype(jnp.int32)}

            def write(c_sect, kr_sect, suffix):
                c_w, kr_w = c_sect, kr_sect
                if quantize:  # int8 latent cache
                    c_w, c_sc = _kv_quant(c_w)       # (L,B,S,r) + (L,B,S)
                    kr_w, kr_sc = _kv_quant(kr_w)
                    new_cache[f"c{suffix}_scale"] = jnp.pad(c_sc, pad4[:-1])
                    new_cache[f"kr{suffix}_scale"] = jnp.pad(kr_sc,
                                                             pad4[:-1])
                new_cache[f"c{suffix}"] = jnp.pad(c_w, pad4)
                new_cache[f"kr{suffix}"] = jnp.pad(kr_w, pad4)

            write(k_all, v_all, "")                 # main stack
            if prefix_kv is not None:               # dense-prefix stack
                write(prefix_kv[0], prefix_kv[1], "_pre")
            return logits, new_cache
        if "k_l" in cache:  # mixed local/global split cache (Gemma-2/3)
            ring = cache["k_l"].shape[2]
            max_g = cache["k_g"].shape[2]
            if s > ring or s > max_g:
                raise ValueError(f"prompt chunk {s} exceeds cache sections "
                                 f"(ring {ring}, global {max_g})")
            n_groups = cfg.n_layers // pat
            loc_shape = (n_groups * (pat - 1),) + k_all.shape[1:]
            pad_l = [(0, 0), (0, 0), (0, ring - s), (0, 0), (0, 0)]
            pad_g = [(0, 0), (0, 0), (0, max_g - s), (0, 0), (0, 0)]
            slot_ids = jnp.arange(ring)[None, :]
            new_cache = {
                "index": true_length.astype(jnp.int32),
                "abs_pos": jnp.where(slot_ids < true_length[:, None],
                                     slot_ids, -1).astype(jnp.int32),
            }
            if "k_l_scale" in cache:  # int8 split cache: quantize first
                k_all, k_sc = _kv_quant(k_all)       # (L,B,S,h,d) + (L,B,S,h)
                v_all, v_sc = _kv_quant(v_all)
                gk_sc = k_sc.reshape((n_groups, pat) + k_sc.shape[1:])
                gv_sc = v_sc.reshape((n_groups, pat) + v_sc.shape[1:])
                loc_sc = loc_shape[:-1]
                new_cache["k_l_scale"] = jnp.pad(
                    gk_sc[:, :pat - 1].reshape(loc_sc), pad_l[:-1])
                new_cache["v_l_scale"] = jnp.pad(
                    gv_sc[:, :pat - 1].reshape(loc_sc), pad_l[:-1])
                new_cache["k_g_scale"] = jnp.pad(gk_sc[:, pat - 1], pad_g[:-1])
                new_cache["v_g_scale"] = jnp.pad(gv_sc[:, pat - 1], pad_g[:-1])
            grouped_k = k_all.reshape((n_groups, pat) + k_all.shape[1:])
            grouped_v = v_all.reshape((n_groups, pat) + v_all.shape[1:])
            new_cache["k_l"] = jnp.pad(
                grouped_k[:, :pat - 1].reshape(loc_shape), pad_l)
            new_cache["v_l"] = jnp.pad(
                grouped_v[:, :pat - 1].reshape(loc_shape), pad_l)
            new_cache["k_g"] = jnp.pad(grouped_k[:, pat - 1], pad_g)
            new_cache["v_g"] = jnp.pad(grouped_v[:, pat - 1], pad_g)
            return logits, new_cache
        max_len = cache["k"].shape[2]
        if s > max_len:
            raise ValueError(f"prompt length {s} exceeds cache length "
                             f"{max_len}")
        pad = [(0, 0), (0, 0), (0, max_len - s), (0, 0), (0, 0)]
        new_cache = {"index": true_length.astype(jnp.int32)}
        if "k_scale" in cache:  # int8 cache: quantize the collected K/V
            k_all, k_sc = _kv_quant(k_all)             # (L,B,S,h,d) + (L,B,S,h)
            v_all, v_sc = _kv_quant(v_all)
            new_cache["k_scale"] = jnp.pad(k_sc, pad[:-1])
            new_cache["v_scale"] = jnp.pad(v_sc, pad[:-1])
        new_cache["k"] = jnp.pad(k_all, pad)
        new_cache["v"] = jnp.pad(v_all, pad)
        if "abs_pos" in cache:  # ring: slots 0..true_len-1 hold those positions
            slot_ids = jnp.arange(max_len)[None, :]
            new_cache["abs_pos"] = jnp.where(
                slot_ids < true_length[:, None], slot_ids, -1).astype(jnp.int32)
        return logits, new_cache

    def decode_step(self, params: Params, token: jax.Array, cache: Params,
                    active: Optional[jax.Array] = None,
                    adapters: Optional[dict] = None,
                    adapter_ids: Optional[jax.Array] = None
                    ) -> tuple[jax.Array, Params]:
        """One token per slot: token (B,) -> (logits (B,V), cache).

        Each slot decodes at its own cache index (continuous batching).
        ``active`` (B,) bool freezes inactive slots: their cache and index
        stay untouched, so idle slots cost compute but not correctness.
        This is the K=1 case of ``verify_step`` (one kernel to maintain),
        plus the index advance the verify path leaves to its caller."""
        if active is None:
            active = jnp.ones((token.shape[0],), bool)
        logits, cache = self.verify_step(params, token[:, None], cache, active,
                                         adapters, adapter_ids)
        cache = dict(cache)
        cache["index"] = jnp.where(active, cache["index"] + 1, cache["index"])
        return logits[:, 0], cache

    def init_paged_arena(self, n_pages: int, page_tokens: int,
                         quantize: bool = False) -> Params:
        """KV page arena for ``paged_decode_step``: per section (L, P, T,
        ...), page-major — page p's T positions are one contiguous tile,
        and a sequence is a page-table row over the shared pool (the
        serving engine's prefix arena uses the identical layout, so pages
        move between the two without reshapes; kv_cache_pspec applies
        verbatim for TP). Covers plain dense K/V, int8 K/V
        (``quantize=True``: int8 payload + per-(position, kv-head) f32
        scale sections paged alongside) and MLA latent layouts (c/kr —
        and c_pre/kr_pre for dense-prefix models — no heads axis),
        INCLUDING the int8 LATENT combination (``quantize=True`` on an
        MLA config: int8 c/kr with per-position f32 scale sections).
        UNIFORM sliding-window models (pattern 1) page too: positions
        store linearly, the decode kernel masks/skips outside the window,
        and the serving engine recycles out-of-window pages through the
        slot's ring run — only the windowed INTERLEAVE (pattern > 1,
        split ring/global cache) cannot page."""
        cfg = self.cfg
        if cfg.sliding_window is not None and cfg.sliding_window_pattern != 1:
            raise ValueError("paged decode covers uniform sliding windows "
                             "only (pattern 1); the windowed interleave's "
                             "split ring/global cache cannot page")
        if cfg.is_mla:
            dt = jnp.int8 if quantize else cfg.dtype
            r, dr = cfg.mla_latent_dim, cfg.mla_rope_dim
            kpre = cfg.n_dense_prefix
            lm = cfg.n_layers - kpre
            arena = {"c": jnp.zeros((lm, n_pages, page_tokens, r), dt),
                     "kr": jnp.zeros((lm, n_pages, page_tokens, dr), dt)}
            if quantize:
                arena["c_scale"] = jnp.zeros((lm, n_pages, page_tokens),
                                             jnp.float32)
                arena["kr_scale"] = jnp.zeros((lm, n_pages, page_tokens),
                                              jnp.float32)
            if kpre:
                arena["c_pre"] = jnp.zeros((kpre, n_pages, page_tokens, r),
                                           dt)
                arena["kr_pre"] = jnp.zeros((kpre, n_pages, page_tokens, dr),
                                            dt)
                if quantize:
                    arena["c_pre_scale"] = jnp.zeros(
                        (kpre, n_pages, page_tokens), jnp.float32)
                    arena["kr_pre_scale"] = jnp.zeros(
                        (kpre, n_pages, page_tokens), jnp.float32)
            return arena
        shape = (cfg.n_layers, n_pages, page_tokens, cfg.n_kv_heads,
                 cfg.head_dim_)
        dt = jnp.int8 if quantize else cfg.dtype
        arena = {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}
        if quantize:
            arena["k_scale"] = jnp.zeros(shape[:-1], jnp.float32)
            arena["v_scale"] = jnp.zeros(shape[:-1], jnp.float32)
        return arena

    @_with_int4_mesh
    def paged_decode_step(self, params: Params, token: jax.Array,
                          arena: Params, page_tables: jax.Array,
                          lengths: jax.Array,
                          active: Optional[jax.Array] = None,
                          adapters: Optional[dict] = None,
                          adapter_ids: Optional[jax.Array] = None, *,
                          use_pallas: Optional[bool] = None,
                          interpret: bool = False,
                          shard_kv: bool = True
                          ) -> tuple[jax.Array, Params, jax.Array]:
        """One decode token per slot over PAGED KV (ops.paged_attention):
        token (B,) -> (logits (B, V) f32, arena, lengths'). Slot b's KV
        lives in pages page_tables[b] of the shared arena; the new token's
        K/V is written at logical position lengths[b] (page pos//T, offset
        pos%%T — the caller allocates a fresh page whenever a slot's
        length crosses a page boundary, so the target entry is always
        this slot's private tail page while matched PREFIX pages stay
        shared copy-on-write). ``active`` freezes slots exactly like
        decode_step. Token-identical to decode_step on the same history
        (tests pin it); this is the decode path disaggregated prefill/
        decode (ROADMAP item 2) ships KV pages into.

        This is the K=1 case of ``paged_verify_step`` (one kernel to
        maintain — the same delegation decode_step makes to verify_step),
        plus the lengths advance the verify path leaves to its caller.
        ``adapters``/``adapter_ids`` thread per-request multi-LoRA deltas
        exactly like decode_step (ISSUE 14 lifted the paged loop's
        no-adapters exclusion).

        Layouts (ISSUE 10 lifted the plain-dense-only gate; ISSUE 11
        finished the matrix): plain K/V, int8 K/V (k_scale/v_scale
        sections page alongside), MLA latents (c/kr ± dense-prefix
        sections), the int8 LATENT combination, and UNIFORM sliding
        windows (the kernels mask/skip outside the window; table entries
        behind ``length - window`` are never read, so the caller may
        recycle their physical pages — the engine's ring run). Only the
        windowed interleave (pattern > 1) still cannot page.

        Mesh serving (ISSUE 12): the attention dispatches run under
        shard_map over ``tensor`` (kv-head axis local per shard when
        ``shard_kv``, fully replicated specs when the engine pinned a
        replicated arena) and the new row's scatter partitions through
        GSPMD — the write lands on the owning shard."""
        b = token.shape[0]
        if active is None:
            active = jnp.ones((b,), bool)
        logits, arena = self.paged_verify_step(
            params, token[:, None], arena, page_tables, lengths, active,
            adapters, adapter_ids, use_pallas=use_pallas,
            interpret=interpret, shard_kv=shard_kv)
        new_lengths = jnp.where(active, lengths + 1, lengths)
        return logits[:, 0], arena, new_lengths

    @_with_int4_mesh
    def paged_verify_step(self, params: Params, tokens: jax.Array,
                          arena: Params, page_tables: jax.Array,
                          lengths: jax.Array,
                          active: Optional[jax.Array] = None,
                          adapters: Optional[dict] = None,
                          adapter_ids: Optional[jax.Array] = None,
                          n_tokens: Optional[jax.Array] = None, *,
                          use_pallas: Optional[bool] = None,
                          interpret: bool = False,
                          shard_kv: bool = True
                          ) -> tuple[jax.Array, Params]:
        """K tokens per slot in ONE pass over PAGED KV (the multi-token
        siblings of ops.paged_attention): tokens (B, K) -> (logits
        (B, K, V) f32, arena). Slot b's query j sits at logical position
        lengths[b] + j; its K/V row scatters into page
        page_tables[b, pos // T] at offset pos %% T, and attention runs
        the causal intra-block mask through ops.paged_attention_multi
        (and the _quant/_mla/_mla_quant siblings), so ``logits[:, j]``
        is exactly what paged_decode_step would produce sequentially —
        speculative verification and paged-native chunked prefill in one
        memory-bound sweep instead of K dispatches.

        ``n_tokens`` (B,) limits how many of the K rows are REAL per
        slot (a prefill chunk's true length; a non-greedy slot riding a
        speculative batch verifies only its 1 committed token): rows at
        or beyond n_tokens[b] scatter nothing — an out-of-bounds page id
        + mode="drop" elides the write, the same hazard-closure the
        single-token step applies to inactive slots, whose stale table
        rows may alias another slot's live tail page — and their logits
        are garbage the caller must ignore. ``active`` is the
        n_tokens = 0 degenerate (kept for decode-step delegation).
        ``lengths`` is NOT advanced — the caller commits the accepted
        prefix, and uncommitted tail pages simply drop back to the pool
        (append-only pages make speculative rollback a refcount
        operation, not the ring-invariant rewind the contiguous
        speculative path needs).

        ``adapters``/``adapter_ids`` thread per-request multi-LoRA
        deltas exactly like the contiguous verify_step (_ml_qkv_deltas,
        the wo delta, and the MLP deltas, all with per-row adapter
        selection) — base-only slots ride adapter id 0's all-zero
        entry."""
        cfg = self.cfg
        if cfg.sliding_window is not None and cfg.sliding_window_pattern != 1:
            raise ValueError("paged decode covers uniform sliding windows "
                             "only (pattern 1); the windowed interleave's "
                             "split ring/global cache cannot page")
        if cfg.is_mla:
            if adapters:
                raise ValueError("multi-LoRA adapters do not target MLA "
                                 "projections; serve MLA models without "
                                 "adapters")
            return self._paged_verify_step_mla(
                params, tokens, arena, page_tables, lengths, active,
                n_tokens, use_pallas=use_pallas, interpret=interpret)
        quant = "k_scale" in arena
        b, kk = tokens.shape
        if active is None:
            active = jnp.ones((b,), bool)
        if n_tokens is None:
            n_tokens = jnp.where(active, kk, 0)
        n_tokens = n_tokens.astype(jnp.int32)
        t = arena["k"].shape[2]
        positions = lengths[:, None] + jnp.arange(kk)[None, :]     # (B,K)
        pages_bk = jnp.take_along_axis(page_tables, positions // t, axis=1)
        write_ok = jnp.arange(kk)[None, :] < n_tokens[:, None]     # (B,K)
        pages_bk = jnp.where(write_ok, pages_bk, arena["k"].shape[1])
        offs = positions % t
        # uniform-window models rotate with the LOCAL table when one
        # exists (pattern == 1 means every layer is the windowed kind)
        cos, sin = _rope_for(_rope_tables(cfg), cfg.sliding_window)
        x = _embed(params, tokens, cfg, self.mesh)               # (B,K,E)
        # kernel contract: its ``lengths`` INCLUDES the K query tokens —
        # query j attends positions <= att_len - K + j = lengths + j
        att_len = lengths + kk

        def block(y, inputs):
            lp, kp, vp = inputs["lp"], inputs["k"], inputs["v"]
            ks, vs = inputs.get("ks"), inputs.get("vs")
            adj = inputs.get("ad")
            h = rms_norm(y, _norm_w(lp["attn_norm"], cfg), cfg.norm_eps)
            q, k, v = _qkv(h, lp, cfg, b, kk)
            q, k, v = _ml_qkv_deltas(h, q, k, v, adj, adapter_ids)
            if cfg.qk_norm:
                q = rms_norm(q, _norm_w(lp["q_norm"], cfg), cfg.norm_eps)
                k = rms_norm(k, _norm_w(lp["k_norm"], cfg), cfg.norm_eps)
            q = apply_rope(q, cos, sin, positions)
            k = apply_rope(k, cos, sin, positions)
            if quant:
                # same per-row symmetric scheme as the contiguous int8
                # cache (_kv_quant), so pages and slot caches interchange
                k_w, k_s = _kv_quant(k)             # (B,K,h,d), (B,K,h)
                v_w, v_s = _kv_quant(v)
                ks = ks.at[pages_bk, offs].set(k_s, mode="drop")
                vs = vs.at[pages_bk, offs].set(v_s, mode="drop")
                kp = kp.at[pages_bk, offs].set(k_w, mode="drop")
                vp = vp.at[pages_bk, offs].set(v_w, mode="drop")
                o = paged_attention_multi_quant(
                    q, kp, vp, ks, vs, page_tables, att_len,
                    sm_scale=cfg.sm_scale,
                    logit_soft_cap=cfg.attn_logit_softcap,
                    sliding_window=cfg.sliding_window,
                    use_pallas=use_pallas, interpret=interpret,
                    mesh=self.mesh, shard_heads=shard_kv)
            else:
                kp = kp.at[pages_bk, offs].set(k, mode="drop")
                vp = vp.at[pages_bk, offs].set(v, mode="drop")
                o = paged_attention_multi(
                    q, kp, vp, page_tables, att_len,
                    sm_scale=cfg.sm_scale,
                    logit_soft_cap=cfg.attn_logit_softcap,
                    sliding_window=cfg.sliding_window,
                    use_pallas=use_pallas, interpret=interpret,
                    mesh=self.mesh, shard_heads=shard_kv)
            o = o.reshape(b, kk,
                          cfg.n_heads * cfg.head_dim_).astype(cfg.dtype)
            o_in = o
            o = _mm(o, lp["wo"], cfg.dtype)
            if adj and "wo" in adj:
                o = o + _ml_delta(o_in, adj["wo"], adapter_ids)
            if cfg.post_norms:
                o = rms_norm(o, _norm_w(lp["attn_post_norm"], cfg),
                             cfg.norm_eps)
            y = y + o
            y, _ = _mlp_block(y, lp, cfg, self.mesh, train=False,
                              ad=adj, ad_ids=adapter_ids)
            out = {"k": kp, "v": vp}
            if quant:
                out["ks"], out["vs"] = ks, vs
            return y, out

        xs = {"lp": _group_layers(params["layers"], 1),
              "k": arena["k"], "v": arena["v"]}
        if quant:
            xs["ks"] = arena["k_scale"]
            xs["vs"] = arena["v_scale"]
        if adapters:
            xs["ad"] = _group_layers(adapters, 1)
        x, new_kv = jax.lax.scan(block, x, xs)
        x = rms_norm(x, _norm_w(params["final_norm"], cfg), cfg.norm_eps)
        logits = _head_logits(x, params, cfg).astype(jnp.float32)  # (B,K,V)
        out = {"k": new_kv["k"], "v": new_kv["v"]}
        if quant:
            out["k_scale"], out["v_scale"] = new_kv["ks"], new_kv["vs"]
        return logits, out

    def _paged_verify_step_mla(self, params: Params, tokens: jax.Array,
                               arena: Params, page_tables: jax.Array,
                               lengths: jax.Array,
                               active: Optional[jax.Array] = None,
                               n_tokens: Optional[jax.Array] = None, *,
                               use_pallas: Optional[bool] = None,
                               interpret: bool = False
                               ) -> tuple[jax.Array, Params]:
        """``paged_verify_step`` for MLA latent arenas, in the ABSORBED
        form (_verify_step_mla's math over pages): each of the K new
        tokens' normed latent c and rope key kr writes at its (page,
        offset) — latents have no heads axis, so a page row is
        (T, r)/(T, dr) — and attention runs latent-space scores + the
        decoupled-RoPE term over the page table
        (ops.paged_attention_multi_mla), never materializing per-head
        K/V. Dense-prefix models' c_pre/kr_pre sections page under the
        SAME page ids; int8 LATENT arenas (``c_scale`` present) quantize
        each new row exactly like the contiguous int8 latent cache and
        attend through ops.paged_attention_multi_mla_quant (dequant in
        kernel). Same n_tokens write-mask and no-lengths-advance
        contract as the plain sibling."""
        cfg = self.cfg
        quant = "c_scale" in arena
        b, kk = tokens.shape
        if active is None:
            active = jnp.ones((b,), bool)
        if n_tokens is None:
            n_tokens = jnp.where(active, kk, 0)
        n_tokens = n_tokens.astype(jnp.int32)
        t = arena["c"].shape[2]
        positions = lengths[:, None] + jnp.arange(kk)[None, :]     # (B,K)
        pages_bk = jnp.take_along_axis(page_tables, positions // t, axis=1)
        # rows at/beyond n_tokens must not scatter (stale table rows
        # alias live tail pages): OOB page id + mode="drop"
        write_ok = jnp.arange(kk)[None, :] < n_tokens[:, None]
        pages_bk = jnp.where(write_ok, pages_bk, arena["c"].shape[1])
        offs = positions % t
        cos, sin = _rope_tables(cfg)[0]          # MLA: single global table
        hd, dr, r = cfg.head_dim_, cfg.mla_rope_dim, cfg.mla_latent_dim
        hn = cfg.n_heads
        scale = (hd + dr) ** -0.5 * yarn_mscale_sq(cfg)
        x = _embed(params, tokens, cfg, self.mesh)               # (B,K,E)
        att_len = lengths + kk

        def make_block(cfg_):
            def block(y, inputs):
                lp, cp, krp = inputs["lp"], inputs["c"], inputs["kr"]
                cs, krs = inputs.get("cs"), inputs.get("krs")
                h = rms_norm(y, _norm_w(lp["attn_norm"], cfg_),
                             cfg_.norm_eps)
                q_nope, q_rope, c1, kr1 = _mla_project(h, lp, cfg_, cos,
                                                       sin, positions, b,
                                                       kk)
                c_w, kr_w = c1, kr1                 # (B,K,r) / (B,K,dr)
                if quant:
                    # same per-position symmetric scheme as the contiguous
                    # int8 latent cache, so pages and slot caches
                    # interchange (and hand off) without requantization
                    c_w, c_s = _kv_quant(c_w)          # i8, (B,K)
                    kr_w, kr_s = _kv_quant(kr_w)
                    cs = cs.at[pages_bk, offs].set(c_s, mode="drop")
                    krs = krs.at[pages_bk, offs].set(kr_s, mode="drop")
                cp = cp.at[pages_bk, offs].set(c_w, mode="drop")
                krp = krp.at[pages_bk, offs].set(kr_w, mode="drop")
                w_uk = lp["w_uk"].reshape(r, hn, hd)
                # absorbed query: the w_uk fold happens HERE, once per
                # step, so attention reads the (r + dr) latents directly
                q_lat = jnp.einsum("bkhd,rhd->bkhr",
                                   q_nope.astype(jnp.float32),
                                   w_uk.astype(jnp.float32))
                if quant:
                    o_lat = paged_attention_multi_mla_quant(
                        q_lat, q_rope.astype(jnp.float32), cp, krp,
                        cs, krs, page_tables, att_len, sm_scale=scale,
                        use_pallas=use_pallas, interpret=interpret,
                        mesh=self.mesh)
                else:
                    o_lat = paged_attention_multi_mla(
                        q_lat, q_rope.astype(jnp.float32), cp, krp,
                        page_tables, att_len, sm_scale=scale,
                        use_pallas=use_pallas, interpret=interpret,
                        mesh=self.mesh)
                w_uv = lp["w_uv"].reshape(r, hn, hd)
                o = jnp.einsum("bkhr,rhd->bkhd",
                               o_lat.astype(jnp.float32),
                               w_uv.astype(jnp.float32))
                o = o.reshape(b, kk, hn * hd).astype(cfg_.dtype)
                o = _mm(o, lp["wo"], cfg_.dtype)
                if cfg_.post_norms:
                    o = rms_norm(o, _norm_w(lp["attn_post_norm"], cfg_),
                                 cfg_.norm_eps)
                y = y + o
                y, _ = _mlp_block(y, lp, cfg_, self.mesh, train=False)
                out = {"c": cp, "kr": krp}
                if quant:
                    out["cs"], out["krs"] = cs, krs
                return y, out
            return block

        def make_xs(lp_tree, suffix):
            xs_ = {"lp": lp_tree, "c": arena[f"c{suffix}"],
                   "kr": arena[f"kr{suffix}"]}
            if quant:
                xs_["cs"] = arena[f"c{suffix}_scale"]
                xs_["krs"] = arena[f"kr{suffix}_scale"]
            return xs_

        new_pre = None
        if cfg.n_dense_prefix:
            x, new_pre = jax.lax.scan(
                make_block(cfg.prefix_cfg()), x,
                make_xs(params["prefix_layers"], "_pre"))
        x, new_kv = jax.lax.scan(make_block(cfg), x,
                                 make_xs(params["layers"], ""))
        x = rms_norm(x, _norm_w(params["final_norm"], cfg), cfg.norm_eps)
        logits = _head_logits(x, params, cfg).astype(jnp.float32)  # (B,K,V)
        out = {"c": new_kv["c"], "kr": new_kv["kr"]}
        if quant:
            out["c_scale"], out["kr_scale"] = new_kv["cs"], new_kv["krs"]
        if new_pre is not None:
            out["c_pre"], out["kr_pre"] = new_pre["c"], new_pre["kr"]
            if quant:
                out["c_pre_scale"] = new_pre["cs"]
                out["kr_pre_scale"] = new_pre["krs"]
        return logits, out

    def paged_prefill_chunk_step(self, params: Params, tokens: jax.Array,
                                 arena: Params, page_tables: jax.Array,
                                 lengths: jax.Array,
                                 true_length: jax.Array,
                                 adapters: Optional[dict] = None,
                                 adapter_ids: Optional[jax.Array] = None, *,
                                 use_pallas: Optional[bool] = None,
                                 interpret: bool = False,
                                 shard_kv: bool = True
                                 ) -> tuple[jax.Array, Params, jax.Array]:
        """One CHUNK of a prompt scattered STRAIGHT INTO arena pages
        (paged-native chunked prefill, ISSUE 14): ``tokens`` (B, S_pad)
        is the chunk zero-padded to its compile bucket, ``true_length``
        (B,) the real token count — TRACED, so chunk lengths never force
        a recompile — and ``lengths`` (B,) how many tokens the run
        already holds (prior chunks + any prefix-cache hit). The chunk's
        K/V rows land at logical positions lengths..lengths+true_length-1
        of the slot's page run: no dense scratch cache, no fill_pages
        copy afterwards — the pages ARE the prefill output, ready for
        decode, trie insertion, or streamed handoff export the moment
        the dispatch returns. Padded rows scatter nothing (n_tokens
        write-mask) and attend garbage nobody reads. Returns (last-real-
        token logits (B, V), arena, lengths + true_length).
        Token-identical to the dense prefill_chunk_step + fill_pages
        route (pinned by tests)."""
        b = tokens.shape[0]
        tl = true_length.astype(jnp.int32)
        logits, arena = self.paged_verify_step(
            params, tokens, arena, page_tables, lengths, None, adapters,
            adapter_ids, n_tokens=tl, use_pallas=use_pallas,
            interpret=interpret, shard_kv=shard_kv)
        return logits[jnp.arange(b), tl - 1], arena, lengths + tl

    def prefill_chunk_step(self, params: Params, tokens: jax.Array,
                           cache: Params, true_length: jax.Array,
                           adapters: Optional[dict] = None,
                           adapter_ids: Optional[jax.Array] = None
                           ) -> tuple[jax.Array, Params]:
        """One CHUNK of a prompt appended to a running single-request
        cache (serving chunked prefill, ISSUE 10): ``tokens`` (B, S_pad)
        is the chunk zero-padded to its compile bucket, ``true_length``
        (B,) the real token count — TRACED, so chunk lengths never force
        a recompile. The chunk consumes the cache's running KV (all prior
        chunks') through the verify kernel; padded positions' KV lands
        beyond the committed index, never attended and overwritten by the
        next chunk (the decode-path invariant), and ``index`` advances by
        ``true_length``. Returns (last-real-token logits (B, V), cache).
        Token-identical to one monolithic prefill of the concatenation
        (pinned by tests) — the win is that the scheduler can interleave
        decode steps between chunk dispatches, so a long prompt no longer
        freezes co-resident streams' ITL."""
        b = tokens.shape[0]
        logits, cache = self.verify_step(params, tokens, cache, None,
                                         adapters, adapter_ids)
        cache = dict(cache)
        tl = true_length.astype(jnp.int32)
        cache["index"] = cache["index"] + tl
        return logits[jnp.arange(b), tl - 1], cache

    @_with_int4_mesh
    def verify_step(self, params: Params, tokens: jax.Array, cache: Params,
                    active: Optional[jax.Array] = None,
                    adapters: Optional[dict] = None,
                    adapter_ids: Optional[jax.Array] = None
                    ) -> tuple[jax.Array, Params]:
        """Speculative-decoding verification: K tokens per slot in ONE pass.

        tokens (B, K) — position j of slot b sits at cache index idx[b]+j
        (token 0 is the slot's last committed token; 1..K-1 are draft
        proposals). Returns (logits (B, K, V) f32, cache): ``logits[:, j]``
        is the next-token distribution after consuming tokens[:, :j+1] —
        exactly what ``decode_step`` would produce sequentially, in one
        memory-bound sweep instead of K.

        All K KV entries are written; the cache ``index`` is NOT advanced —
        the caller commits the accepted prefix by setting ``index += m``.
        Rejected positions hold garbage KV but stay invisible: attention
        masks to ``<= index`` and later writes overwrite them (the same
        invariant decode_step relies on)."""
        cfg = self.cfg
        if cfg.is_mla:
            return self._verify_step_mla(params, tokens, cache, active,
                                         adapters, adapter_ids)
        b, kk = tokens.shape
        idx = cache["index"]  # (B,)
        if active is None:
            active = jnp.ones((b,), bool)
        ropes = _rope_tables(cfg)
        x = _embed(params, tokens, cfg, self.mesh)                 # (B,K,E)
        positions = idx[:, None] + jnp.arange(kk)[None, :]         # (B,K)
        pat = cfg.sliding_window_pattern
        windows = cfg.layer_windows()
        batch_ids = jnp.arange(b)[:, None]                         # (B,1)
        mixed = "k_l" in cache   # split local-ring/global cache (Gemma-2/3)
        ring = (not mixed) and "abs_pos" in cache

        def ring_state(ring_len):
            # ring addressing: position p writes slot p % R; the mask comes
            # from abs_pos AFTER this call's writes (every ring layer writes
            # the same slots, so one abs_pos array serves the whole scan).
            # Slots holding not-yet-committed draft positions (> idx+j) fail
            # the causal test, so rejected-draft garbage stays invisible
            # until genuinely overwritten.
            slots_r = positions % ring_len                         # (B,K)
            old_abs = cache["abs_pos"][batch_ids, slots_r]
            return slots_r, cache["abs_pos"].at[batch_ids, slots_r].set(
                jnp.where(active[:, None], positions, old_abs))

        def make_mask(pos_l, win):
            # (B,1,1,K,L): query j of slot b attends positions <= idx[b]+j
            cv = (pos_l >= 0) & (pos_l <= positions[:, :, None])
            if win is not None:
                cv &= (positions[:, :, None] - pos_l) < win
            return cv[:, None, None]

        new_abs = None
        if mixed:
            slots_loc, new_abs = ring_state(cache["k_l"].shape[2])
            pos_loc = new_abs[:, None, :]
            pos_glob = jnp.arange(cache["k_g"].shape[2])[None, None, :]
            masks = [make_mask(pos_loc if windows[j] is not None else pos_glob,
                               windows[j]) for j in range(pat)]
            slot_map = [slots_loc if windows[j] is not None else positions
                        for j in range(pat)]
        elif ring:
            slots_r, new_abs = ring_state(cache["k"].shape[2])
            masks = [make_mask(new_abs[:, None, :], win) for win in windows]
            slot_map = [slots_r] * pat
        else:
            pos_l = jnp.arange(cache["k"].shape[2])[None, None, :]
            masks = [make_mask(pos_l, win) for win in windows]
            slot_map = [positions] * pat

        quant = "k_scale" in cache or "k_l_scale" in cache

        def sub_block(y, lp, k_cache, v_cache, k_scale, v_scale, valid, rope,
                      adj, slots):
            cos, sin = rope
            h = rms_norm(y, _norm_w(lp["attn_norm"], cfg), cfg.norm_eps)
            q, k, v = _qkv(h, lp, cfg, b, kk)
            q, k, v = _ml_qkv_deltas(h, q, k, v, adj, adapter_ids)
            if cfg.qk_norm:
                q = rms_norm(q, _norm_w(lp["q_norm"], cfg), cfg.norm_eps)
                k = rms_norm(k, _norm_w(lp["k_norm"], cfg), cfg.norm_eps)
            q = apply_rope(q, cos, sin, positions)
            k = apply_rope(k, cos, sin, positions)
            act3 = active[:, None, None]
            act4 = active[:, None, None, None]
            if quant:  # int8 cache: quantize the new rows, scales alongside
                k, k_s = _kv_quant(k)                          # i8, (B,K,h)
                v, v_s = _kv_quant(v)
                k_scale = k_scale.at[batch_ids, slots].set(
                    jnp.where(act3, k_s, k_scale[batch_ids, slots]))
                v_scale = v_scale.at[batch_ids, slots].set(
                    jnp.where(act3, v_s, v_scale[batch_ids, slots]))
            old_k = k_cache[batch_ids, slots]                      # (B,K,h,d)
            old_v = v_cache[batch_ids, slots]
            k_cache = k_cache.at[batch_ids, slots].set(
                jnp.where(act4, k, old_k))
            v_cache = v_cache.at[batch_ids, slots].set(
                jnp.where(act4, v, old_v))
            k_read = (_kv_dequant(k_cache, k_scale) if quant
                      else k_cache.astype(jnp.float32))
            v_read = (_kv_dequant(v_cache, v_scale) if quant
                      else v_cache.astype(jnp.float32))
            group = cfg.n_heads // cfg.n_kv_heads
            qg = (q.astype(jnp.float32) * cfg.sm_scale
                  ).reshape(b, kk, cfg.n_kv_heads, group, cfg.head_dim_)
            s = jnp.einsum("bqhgd,bLhd->bhgqL", qg, k_read)
            if cfg.attn_logit_softcap is not None:
                cap = cfg.attn_logit_softcap
                s = jnp.tanh(s / cap) * cap
            s = jnp.where(valid, s, -1e30)
            p = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("bhgqL,bLhd->bqhgd", p, v_read)
            o = o.reshape(b, kk, cfg.n_heads * cfg.head_dim_).astype(cfg.dtype)
            o_in = o
            o = _mm(o, lp["wo"], cfg.dtype)
            if adj and "wo" in adj:
                o = o + _ml_delta(o_in, adj["wo"], adapter_ids)
            if cfg.post_norms:
                o = rms_norm(o, _norm_w(lp["attn_post_norm"], cfg),
                             cfg.norm_eps)
            y = y + o
            y, _ = _mlp_block(y, lp, cfg, self.mesh, train=False,
                              ad=adj, ad_ids=adapter_ids)
            return y, k_cache, v_cache, k_scale, v_scale

        def block(carry, inputs):
            y = carry
            lp_g = inputs["lp"]
            ad_g = inputs.get("ad")
            if mixed:
                kl, vl = inputs["kl"], inputs["vl"]   # (p-1, B, R, h, d)
                kgl, vgl = inputs["kg"], inputs["vg"]  # (B, G, h, d)
                kls, vls = inputs.get("kls"), inputs.get("vls")
                kgs, vgs = inputs.get("kgs"), inputs.get("vgs")
                kl_out, vl_out, kls_out, vls_out = [], [], [], []
                kg_out = vg_out = kgs_out = vgs_out = None
                for j in range(pat):
                    local = windows[j] is not None
                    y, k_n, v_n, ks_n, vs_n = sub_block(
                        y, _sublayer(lp_g, j, pat),
                        kl[j] if local else kgl,
                        vl[j] if local else vgl,
                        None if kls is None else (kls[j] if local else kgs),
                        None if vls is None else (vls[j] if local else vgs),
                        masks[j], _rope_for(ropes, windows[j]),
                        None if ad_g is None else _sublayer(ad_g, j, pat),
                        slot_map[j])
                    if local:
                        kl_out.append(k_n)
                        vl_out.append(v_n)
                        if quant:
                            kls_out.append(ks_n)
                            vls_out.append(vs_n)
                    else:
                        kg_out, vg_out = k_n, v_n
                        if quant:
                            kgs_out, vgs_out = ks_n, vs_n
                out = {"kl": jnp.stack(kl_out), "vl": jnp.stack(vl_out),
                       "kg": kg_out, "vg": vg_out}
                if quant:
                    out.update(kls=jnp.stack(kls_out),
                               vls=jnp.stack(vls_out),
                               kgs=kgs_out, vgs=vgs_out)
                return y, out
            k_g, v_g = inputs["k"], inputs["v"]
            ks_g, vs_g = inputs.get("ks"), inputs.get("vs")
            if pat == 1:
                y, k_n, v_n, ks_n, vs_n = sub_block(
                    y, lp_g, k_g, v_g, ks_g, vs_g, masks[0],
                    _rope_for(ropes, windows[0]), ad_g, slot_map[0])
                out = {"k": k_n, "v": v_n}
                if quant:
                    out["ks"], out["vs"] = ks_n, vs_n
                return y, out
            outs: dict[str, list] = {"k": [], "v": [], "ks": [], "vs": []}
            for j in range(pat):
                y, k_n, v_n, ks_n, vs_n = sub_block(
                    y, _sublayer(lp_g, j, pat), k_g[j], v_g[j],
                    None if ks_g is None else ks_g[j],
                    None if vs_g is None else vs_g[j], masks[j],
                    _rope_for(ropes, windows[j]),
                    None if ad_g is None else _sublayer(ad_g, j, pat),
                    slot_map[j])
                outs["k"].append(k_n)
                outs["v"].append(v_n)
                if quant:
                    outs["ks"].append(ks_n)
                    outs["vs"].append(vs_n)
            return y, {kk_: jnp.stack(v_) for kk_, v_ in outs.items() if v_}

        xs = {"lp": _group_layers(params["layers"], pat)}
        if mixed:
            n_groups = cfg.n_layers // pat
            xs["kl"] = cache["k_l"].reshape(
                (n_groups, pat - 1) + cache["k_l"].shape[1:])
            xs["vl"] = cache["v_l"].reshape(
                (n_groups, pat - 1) + cache["v_l"].shape[1:])
            xs["kg"] = cache["k_g"]
            xs["vg"] = cache["v_g"]
            if quant:
                xs["kls"] = cache["k_l_scale"].reshape(
                    (n_groups, pat - 1) + cache["k_l_scale"].shape[1:])
                xs["vls"] = cache["v_l_scale"].reshape(
                    (n_groups, pat - 1) + cache["v_l_scale"].shape[1:])
                xs["kgs"] = cache["k_g_scale"]
                xs["vgs"] = cache["v_g_scale"]
        else:
            xs["k"] = _group_layers(cache["k"], pat)
            xs["v"] = _group_layers(cache["v"], pat)
            if quant:
                xs["ks"] = _group_layers(cache["k_scale"], pat)
                xs["vs"] = _group_layers(cache["v_scale"], pat)
        if adapters:
            xs["ad"] = _group_layers(adapters, pat)
        x, new_kv = jax.lax.scan(block, x, xs)
        x = rms_norm(x, _norm_w(params["final_norm"], cfg), cfg.norm_eps)
        logits = _head_logits(x, params, cfg).astype(jnp.float32)  # (B,K,V)
        if mixed:
            nl = new_kv["kl"]  # (n_groups, p-1, B, R, h, d)
            out = {"k_l": nl.reshape((-1,) + nl.shape[2:]),
                   "v_l": new_kv["vl"].reshape((-1,) + nl.shape[2:]),
                   "k_g": new_kv["kg"], "v_g": new_kv["vg"],
                   "index": idx, "abs_pos": new_abs}
            if quant:
                nls = new_kv["kls"]  # (n_groups, p-1, B, R, h)
                out["k_l_scale"] = nls.reshape((-1,) + nls.shape[2:])
                out["v_l_scale"] = new_kv["vls"].reshape(
                    (-1,) + nls.shape[2:])
                out["k_g_scale"] = new_kv["kgs"]
                out["v_g_scale"] = new_kv["vgs"]
            return logits, out
        if pat > 1:  # (L//p, p, B, L, ...) -> (L, B, L, ...)
            new_kv = {kk_: a.reshape((cfg.n_layers,) + a.shape[2:])
                      for kk_, a in new_kv.items()}
        out = {"k": new_kv["k"], "v": new_kv["v"], "index": idx}
        if quant:
            out["k_scale"], out["v_scale"] = new_kv["ks"], new_kv["vs"]
        if ring:
            out["abs_pos"] = new_abs
        return logits, out

    def _verify_step_mla(self, params: Params, tokens: jax.Array,
                         cache: Params,
                         active: Optional[jax.Array] = None,
                         adapters: Optional[dict] = None,
                         adapter_ids: Optional[jax.Array] = None
                         ) -> tuple[jax.Array, Params]:
        """verify_step for MLA models, in the ABSORBED form: fold w_uk into
        the query (q_lat = q_nope @ w_uk) and w_uv into the output, so each
        step reads the (L, r+dr) latent cache and never materializes
        per-head K/V — the bandwidth win the latent compression promised
        (ops/mla.py mla_decode_step is the self-contained single-token
        statement of the same math; this is its K-token, int8-capable,
        active-masked engine sibling). Same contract as verify_step:
        all K latents written, ``index`` NOT advanced, rejected positions
        invisible behind the <= index+j mask."""
        cfg = self.cfg
        if adapters:
            raise ValueError("multi-LoRA adapters do not target MLA "
                             "projections; serve MLA models without "
                             "adapters")
        b, kk = tokens.shape
        idx = cache["index"]
        if active is None:
            active = jnp.ones((b,), bool)
        cos, sin = _rope_tables(cfg)[0]            # MLA: single global table
        x = _embed(params, tokens, cfg, self.mesh)                 # (B,K,E)
        positions = idx[:, None] + jnp.arange(kk)[None, :]         # (B,K)
        batch_ids = jnp.arange(b)[:, None]
        cache_len = cache["c"].shape[2]
        hd, dr, r = cfg.head_dim_, cfg.mla_rope_dim, cfg.mla_latent_dim
        hn = cfg.n_heads
        scale = (hd + dr) ** -0.5 * yarn_mscale_sq(cfg)
        # (B,1,K,L): query j of slot b sees committed positions <= idx[b]+j
        pos_l = jnp.arange(cache_len)[None, None, :]
        valid = (pos_l <= positions[:, :, None])[:, None]
        quant = "c_scale" in cache
        act2 = active[:, None]                     # (B,1) vs (B,K) writes
        act3 = active[:, None, None]

        def make_block(cfg_):
            def block(carry, inputs):
                return _mla_verify_block(carry, inputs, cfg_)
            return block

        def _mla_verify_block(y, inputs, cfg_):
            lp = inputs["lp"]
            c_cache, kr_cache = inputs["c"], inputs["kr"]
            c_sc, kr_sc = inputs.get("cs"), inputs.get("krs")
            h = rms_norm(y, _norm_w(lp["attn_norm"], cfg_), cfg_.norm_eps)
            q_nope, q_rope, c1, kr1 = _mla_project(h, lp, cfg_, cos, sin,
                                                   positions, b, kk)
            if quant:  # int8 latent cache: per-position scales
                c1, c1_s = _kv_quant(c1)                       # (B,K,r),(B,K)
                kr1, kr1_s = _kv_quant(kr1)
                c_sc = c_sc.at[batch_ids, positions].set(
                    jnp.where(act2, c1_s, c_sc[batch_ids, positions]))
                kr_sc = kr_sc.at[batch_ids, positions].set(
                    jnp.where(act2, kr1_s, kr_sc[batch_ids, positions]))
            c_cache = c_cache.at[batch_ids, positions].set(
                jnp.where(act3, c1, c_cache[batch_ids, positions]))
            kr_cache = kr_cache.at[batch_ids, positions].set(
                jnp.where(act3, kr1, kr_cache[batch_ids, positions]))
            c_read = (_kv_dequant(c_cache, c_sc) if quant
                      else c_cache.astype(jnp.float32))        # (B,L,r)
            kr_read = (_kv_dequant(kr_cache, kr_sc) if quant
                       else kr_cache.astype(jnp.float32))      # (B,L,dr)
            w_uk = lp["w_uk"].reshape(r, hn, hd)
            # absorbed query: latent-space scores + decoupled-RoPE term
            q_lat = jnp.einsum("bkhd,rhd->bkhr",
                               q_nope.astype(jnp.float32) * scale,
                               w_uk.astype(jnp.float32))
            s = (jnp.einsum("bkhr,blr->bhkl", q_lat, c_read)
                 + jnp.einsum("bkhd,bld->bhkl",
                              q_rope.astype(jnp.float32) * scale, kr_read))
            s = jnp.where(valid, s, -1e30)
            p = jax.nn.softmax(s, axis=-1)
            o_lat = jnp.einsum("bhkl,blr->bkhr", p, c_read)    # (B,K,H,r)
            w_uv = lp["w_uv"].reshape(r, hn, hd)
            o = jnp.einsum("bkhr,rhd->bkhd", o_lat,
                           w_uv.astype(jnp.float32))
            o = o.reshape(b, kk, hn * hd).astype(cfg_.dtype)
            o = _mm(o, lp["wo"], cfg_.dtype)
            if cfg_.post_norms:
                o = rms_norm(o, _norm_w(lp["attn_post_norm"], cfg_),
                             cfg_.norm_eps)
            y = y + o
            y, _ = _mlp_block(y, lp, cfg_, self.mesh, train=False)
            out = {"c": c_cache, "kr": kr_cache}
            if quant:
                out["cs"], out["krs"] = c_sc, kr_sc
            return y, out

        def make_xs(lp_tree, suffix):
            xs_ = {"lp": lp_tree, "c": cache[f"c{suffix}"],
                   "kr": cache[f"kr{suffix}"]}
            if quant:
                xs_["cs"] = cache[f"c{suffix}_scale"]
                xs_["krs"] = cache[f"kr{suffix}_scale"]
            return xs_

        # dense-prefix layers carry their OWN cache sections (c_pre/kr_pre):
        # no slicing or re-concatenation of the (L, ...) cache per step, so
        # the donated buffers alias straight through both scans
        new_kv_pre = None
        if cfg.n_dense_prefix:
            x, new_kv_pre = jax.lax.scan(
                make_block(cfg.prefix_cfg()), x,
                make_xs(params["prefix_layers"], "_pre"))
        x, new_kv = jax.lax.scan(make_block(cfg), x,
                                 make_xs(params["layers"], ""))
        x = rms_norm(x, _norm_w(params["final_norm"], cfg), cfg.norm_eps)
        logits = _head_logits(x, params, cfg).astype(jnp.float32)  # (B,K,V)
        out = {"c": new_kv["c"], "kr": new_kv["kr"], "index": idx}
        if quant:
            out["c_scale"], out["kr_scale"] = new_kv["cs"], new_kv["krs"]
        if new_kv_pre is not None:
            out["c_pre"], out["kr_pre"] = new_kv_pre["c"], new_kv_pre["kr"]
            if quant:
                out["c_pre_scale"] = new_kv_pre["cs"]
                out["kr_pre_scale"] = new_kv_pre["krs"]
        return logits, out

    @staticmethod
    def insert_into_slot(cache: Params, single: Params, slot: int | jax.Array
                         ) -> Params:
        """Place a freshly-prefilled single-request cache (batch 1) into slot
        ``slot`` of the serving cache (continuous batching admission)."""
        out = {"index": cache["index"].at[slot].set(single["index"][0])}
        # every stacked-KV section shares the (layers, batch, ...) layout
        for sect in ("k", "v", "k_l", "v_l", "k_g", "v_g",
                     "k_scale", "v_scale", "k_l_scale", "v_l_scale",
                     "k_g_scale", "v_g_scale",
                     "c", "kr", "c_scale", "kr_scale",
                     "c_pre", "kr_pre", "c_pre_scale", "kr_pre_scale"):
            if sect in cache:
                out[sect] = cache[sect].at[:, slot].set(single[sect][:, 0])
        if "abs_pos" in cache:
            out["abs_pos"] = cache["abs_pos"].at[slot].set(single["abs_pos"][0])
        return out
