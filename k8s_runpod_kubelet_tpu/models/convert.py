"""HuggingFace checkpoint import: load Llama/Qwen2/Gemma/Mixtral weights into
this framework's param pytree.

The reference framework ships opaque containers, so its users bring their own
weights; ours are typically published in HF format. This converter makes the
switch one call: ``params = load_hf(cfg, path_or_state_dict)``. Correctness is
proven the strong way in tests/test_hf_convert.py — logits parity against the
``transformers`` reference implementation on randomly-initialized tiny models
of every supported family (which also pins down our architecture fidelity:
RoPE convention, GQA layout, norm placement, activation, MoE routing).

Mapping notes:
- HF ``nn.Linear`` stores (out, in); our matmuls are x @ W with (in, out) —
  every projection transposes.
- Our layer leaves are STACKED with a leading (n_layers, ...) axis (the
  forward is one lax.scan over layers), so per-layer HF tensors stack.
- RoPE: both sides use the rotate-half pairing, so q/k convert untouched.
- Gemma: HF stores RMSNorm weights zero-centered (applied as 1+w) and scales
  embeddings by sqrt(E) in forward — both match cfg flags, no weight munging.
- Mixtral: experts e.w1/w3/w2 are gate/up/down; the router is ``gate``.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Mapping, Optional, Union

import numpy as np

from .llama import LlamaConfig, Params

__all__ = ["from_hf_state_dict", "load_hf", "to_hf_state_dict"]


def _np(t, dt: np.dtype) -> np.ndarray:
    """torch tensor / np array -> numpy in the TARGET dtype. Casting per
    tensor at read time (instead of a whole-tree f32 pass at the end) bounds
    peak host RAM to checkpoint + converted tree + ONE transient tensor —
    a 70B bf16 checkpoint converts without a ~4x f32 blowup."""
    if hasattr(t, "detach"):  # torch.Tensor without importing torch
        t = t.detach().cpu()
        if str(t.dtype) in ("torch.bfloat16", "torch.float16"):
            t = t.float()  # transient f32, this one tensor only
        t = t.numpy()
    return np.asarray(t).astype(dt, copy=False)


def _stack(sd: Mapping[str, Any], fmt: str, n_layers: int, dt: np.dtype,
           transpose: bool = False, offset: int = 0) -> np.ndarray:
    outs = []
    for i in range(offset, offset + n_layers):
        name = fmt.format(i=i)
        if name not in sd:
            raise KeyError(f"HF checkpoint missing {name!r}")
        w = _np(sd[name], dt)
        outs.append(w.T if transpose else w)
    return np.stack(outs)


def _rope_deinterleave(w: np.ndarray, dr: int) -> np.ndarray:
    """Permute the LAST ``dr`` columns of a projection from DeepSeek's
    pair-interleaved RoPE layout to our rotate-half layout.

    DeepSeek-V2 rotates (x0,x1),(x2,x3),... as complex pairs
    (apply_rotary_emb: view_as_complex on reshape(..., -1, 2)); our
    apply_rope rotates ([first half], [second half]). Moving checkpoint
    column 2i -> i and 2i+1 -> dr/2+i makes the two conventions compute
    the IDENTICAL rotation — proven by the logits-parity test against
    transformers' DeepseekV2ForCausalLM."""
    perm = np.concatenate([np.arange(0, dr, 2), np.arange(1, dr, 2)])
    out = w.copy()
    out[..., -dr:] = w[..., -dr:][..., perm]
    return out


def _rope_reinterleave(w: np.ndarray, dr: int) -> np.ndarray:
    """Inverse of _rope_deinterleave (export)."""
    inv = np.empty(dr, np.int64)
    inv[np.concatenate([np.arange(0, dr, 2), np.arange(1, dr, 2)])] = \
        np.arange(dr)
    out = w.copy()
    out[..., -dr:] = w[..., -dr:][..., inv]
    return out


def _mla_attn_from_hf(cfg: LlamaConfig, sd: Mapping[str, Any],
                      dt: np.dtype, offset: int = 0) -> dict[str, np.ndarray]:
    """DeepSeek-V2 MLA attention mapping (per layer):
      q_proj (H*(dh+dr), E)            -> wq (E, H, dh+dr flat), rope tail
                                          de-interleaved per head
      kv_a_proj_with_mqa (r+dr, E)     -> w_dkv (E, r+dr), rope tail
                                          de-interleaved
      kv_a_layernorm (r,)              -> c_norm
      kv_b_proj (H*(dh+dv), r)         -> w_uk (r, H*dh) + w_uv (r, H*dv)
                                          (per head: [k_nope; v])
      o_proj (E, H*dv)                 -> wo (H*dv, E)
    """
    L = cfg.n_layers
    hd, dr, r = cfg.head_dim_, cfg.mla_rope_dim, cfg.mla_latent_dim
    hn = cfg.n_heads
    out_q: dict[str, list] = {}
    wdkv, cnorm, wuk, wuv, wo = [], [], [], [], []
    for i in range(offset, offset + L):
        p = f"layers.{i}.self_attn."
        if cfg.mla_q_lora_rank is not None:
            # low-rank q: q_a_proj -> wq_a, q_a_layernorm -> q_a_norm,
            # q_b_proj -> wq_b (rope tail de-interleaved per head)
            out_q.setdefault("w_qa", []).append(
                _np(sd[p + "q_a_proj.weight"], dt).T)
            out_q.setdefault("q_a_norm", []).append(
                _np(sd[p + "q_a_layernorm.weight"], dt))
            qb = _np(sd[p + "q_b_proj.weight"], dt).T   # (qr, H*(dh+dr))
            qb = qb.reshape(qb.shape[0], hn, hd + dr)
            out_q.setdefault("w_qb", []).append(
                _rope_deinterleave(qb, dr).reshape(qb.shape[0], -1))
        else:
            q = _np(sd[p + "q_proj.weight"], dt).T      # (E, H*(dh+dr))
            q = q.reshape(q.shape[0], hn, hd + dr)
            out_q.setdefault("wq", []).append(
                _rope_deinterleave(q, dr).reshape(q.shape[0], -1))
        a = _np(sd[p + "kv_a_proj_with_mqa.weight"], dt).T   # (E, r+dr)
        wdkv.append(_rope_deinterleave(a, dr))
        cnorm.append(_np(sd[p + "kv_a_layernorm.weight"], dt))
        b = _np(sd[p + "kv_b_proj.weight"], dt).T       # (r, H*(dh+dv))
        b = b.reshape(r, hn, -1)
        dv = b.shape[-1] - hd
        if dv != hd:
            raise NotImplementedError(
                f"v_head_dim {dv} != qk_nope_head_dim {hd}: this family "
                "assumes square heads (true for V2-Lite)")
        wuk.append(b[:, :, :hd].reshape(r, hn * hd))
        wuv.append(b[:, :, hd:].reshape(r, hn * hd))
        wo.append(_np(sd[p + "o_proj.weight"], dt).T)
    return {**{name: np.stack(v) for name, v in out_q.items()},
            "w_dkv": np.stack(wdkv),
            "c_norm": np.stack(cnorm), "w_uk": np.stack(wuk),
            "w_uv": np.stack(wuv), "wo": np.stack(wo)}


def _check_mla_keys(cfg: LlamaConfig, keys) -> None:
    """Pure key-name checks for DeepSeek-family checkpoints, run BEFORE any
    tensor is read or converted (a real V2 checkpoint is hundreds of GB;
    rejections must cost metadata, not RAM)."""
    if not cfg.is_mla:
        return
    names = {k[len("model."):] if k.startswith("model.") else k
             for k in keys}
    has_q_lora = "layers.0.self_attn.q_a_proj.weight" in names
    if has_q_lora and cfg.mla_q_lora_rank is None:
        raise NotImplementedError(
            "checkpoint uses low-rank q (q_lora_rank, DeepSeek-V2 full) "
            "but the config has mla_q_lora_rank=None; set it to the "
            "checkpoint's q_lora_rank")
    if not has_q_lora and cfg.mla_q_lora_rank is not None:
        raise NotImplementedError(
            f"config expects low-rank q (mla_q_lora_rank="
            f"{cfg.mla_q_lora_rank}) but the checkpoint has a full-rank "
            "q_proj; set mla_q_lora_rank=None")
    if cfg.n_experts and any(".mlp.experts." in k for k in names):
        kpre = cfg.n_dense_prefix
        for i in range(cfg.n_layers):
            has_experts = (f"layers.{i}.mlp.experts.0.gate_proj.weight"
                           in names)
            if i < kpre and has_experts:
                raise NotImplementedError(
                    f"layer {i} has experts but the config expects a dense "
                    f"prefix of {kpre} (n_dense_prefix mismatch — check "
                    "the checkpoint's first_k_dense_replace)")
            if i >= kpre and not has_experts:
                raise NotImplementedError(
                    f"layer {i} has a dense MLP where experts are expected "
                    "(the checkpoint's first_k_dense_replace exceeds the "
                    f"config's n_dense_prefix={kpre}); set n_dense_prefix "
                    "to match")


def from_hf_state_dict(cfg: LlamaConfig, sd: Mapping[str, Any],
                       dtype: Optional[Any] = None) -> Params:
    """Map a HF ``model.state_dict()``-shaped mapping onto our param tree.

    Handles the ``model.`` prefix being present or absent. ``dtype`` defaults
    to cfg.param_dtype. Leaves come back as HOST (numpy) arrays — committing
    them to devices is the caller's job (device_put with its shardings), so a
    model bigger than one chip's HBM never materializes on device 0 first.
    """

    # normalize: strip a leading "model." so both full-model and bare
    # state dicts work; keep lm_head at top level
    norm: dict[str, Any] = {}
    for k, v in sd.items():
        norm[k[len("model."):] if k.startswith("model.") else k] = v
    sd = norm
    _check_mla_keys(cfg, sd.keys())   # before ANY conversion work
    dt = np.dtype(dtype or cfg.param_dtype)  # jnp.bfloat16 works via ml_dtypes
    layers = _hf_layer_stack(cfg.main_cfg(), sd, dt,
                             offset=cfg.n_dense_prefix)
    params: Params = {
        "tok_embed": _np(sd["embed_tokens.weight"], dt),
        "final_norm": _np(sd["norm.weight"], dt),
        "layers": layers,
    }
    if cfg.n_dense_prefix:
        params["prefix_layers"] = _hf_layer_stack(cfg.prefix_cfg(), sd, dt,
                                                  offset=0)
    if not cfg.tie_embeddings:
        if "lm_head.weight" in sd:
            params["lm_head"] = _np(sd["lm_head.weight"], dt).T
        else:  # checkpoint ties but config doesn't: materialize the tie
            params["lm_head"] = params["tok_embed"].T.copy()
    return params


def _hf_layer_stack(cfg: LlamaConfig, sd: Mapping[str, Any], dt: np.dtype,
                    offset: int = 0) -> dict[str, np.ndarray]:
    """One stacked layer group (main or dense-prefix) from HF keys
    ``layers.{offset}..{offset+n_layers-1}``."""
    L = cfg.n_layers
    pre = "layers.{i}."

    layers: dict[str, np.ndarray] = {
        "attn_norm": _stack(sd, pre + "input_layernorm.weight", L, dt,
                            offset=offset),
    }
    if cfg.is_mla:
        layers.update(_mla_attn_from_hf(cfg, sd, dt, offset=offset))
    else:
        layers.update({
            "wq": _stack(sd, pre + "self_attn.q_proj.weight", L, dt,
                         transpose=True),
            "wk": _stack(sd, pre + "self_attn.k_proj.weight", L, dt,
                         transpose=True),
            "wv": _stack(sd, pre + "self_attn.v_proj.weight", L, dt,
                         transpose=True),
            "wo": _stack(sd, pre + "self_attn.o_proj.weight", L, dt,
                         transpose=True),
        })
    if cfg.post_norms:
        # Gemma-2 sandwich norms: HF's post_attention_layernorm is the
        # POST-attention output norm; the pre-MLP norm is
        # pre_feedforward_layernorm
        layers["attn_post_norm"] = _stack(
            sd, pre + "post_attention_layernorm.weight", L, dt, offset=offset)
        layers["mlp_norm"] = _stack(
            sd, pre + "pre_feedforward_layernorm.weight", L, dt,
            offset=offset)
        layers["mlp_post_norm"] = _stack(
            sd, pre + "post_feedforward_layernorm.weight", L, dt,
            offset=offset)
    else:
        layers["mlp_norm"] = _stack(
            sd, pre + "post_attention_layernorm.weight", L, dt, offset=offset)
    if cfg.qk_norm:
        layers["q_norm"] = _stack(sd, pre + "self_attn.q_norm.weight", L, dt,
                                  offset=offset)
        layers["k_norm"] = _stack(sd, pre + "self_attn.k_norm.weight", L, dt,
                                  offset=offset)
    if cfg.qkv_bias:
        layers["wq_b"] = _stack(sd, pre + "self_attn.q_proj.bias", L, dt,
                                offset=offset)
        layers["wk_b"] = _stack(sd, pre + "self_attn.k_proj.bias", L, dt,
                                offset=offset)
        layers["wv_b"] = _stack(sd, pre + "self_attn.v_proj.bias", L, dt,
                                offset=offset)
    if cfg.n_experts:
        deepseek_moe = any(".mlp.experts." in k for k in sd)
        if deepseek_moe:  # prefix consistency enforced by _check_mla_keys
            layers["router"] = _stack(sd, pre + "mlp.gate.weight", L, dt,
                                      transpose=True, offset=offset)
            if cfg.router_sigmoid_bias:  # V3 e_score_correction_bias
                layers["router_bias"] = _stack(
                    sd, pre + "mlp.gate.e_score_correction_bias", L,
                    np.dtype(np.float32), offset=offset)
            names = ("gate_proj", "up_proj", "down_proj")
            expert_fmt = "layers.{i}.mlp.experts.{e}.{w}.weight"
        else:
            layers["router"] = _stack(sd, pre + "block_sparse_moe.gate.weight",
                                      L, dt, transpose=True, offset=offset)
            names = ("w1", "w3", "w2")
            expert_fmt = "layers.{i}.block_sparse_moe.experts.{e}.{w}.weight"
        gates, ups, downs = [], [], []
        for i in range(offset, offset + L):
            g = [_np(sd[expert_fmt.format(i=i, e=e, w=names[0])], dt).T
                 for e in range(cfg.n_experts)]
            u = [_np(sd[expert_fmt.format(i=i, e=e, w=names[1])], dt).T
                 for e in range(cfg.n_experts)]
            d = [_np(sd[expert_fmt.format(i=i, e=e, w=names[2])], dt).T
                 for e in range(cfg.n_experts)]
            gates.append(np.stack(g))
            ups.append(np.stack(u))
            downs.append(np.stack(d))
        layers["we_gate"] = np.stack(gates)
        layers["we_up"] = np.stack(ups)
        layers["we_down"] = np.stack(downs)
        if cfg.n_shared_experts:
            layers["ws_gate"] = _stack(
                sd, pre + "mlp.shared_experts.gate_proj.weight", L, dt,
                transpose=True, offset=offset)
            layers["ws_up"] = _stack(
                sd, pre + "mlp.shared_experts.up_proj.weight", L, dt,
                transpose=True, offset=offset)
            layers["ws_down"] = _stack(
                sd, pre + "mlp.shared_experts.down_proj.weight", L, dt,
                transpose=True, offset=offset)
    else:
        layers["w_gate"] = _stack(sd, pre + "mlp.gate_proj.weight", L, dt,
                                  transpose=True, offset=offset)
        layers["w_up"] = _stack(sd, pre + "mlp.up_proj.weight", L, dt,
                                transpose=True, offset=offset)
        layers["w_down"] = _stack(sd, pre + "mlp.down_proj.weight", L, dt,
                                  transpose=True, offset=offset)
    return layers


def to_hf_state_dict(cfg: LlamaConfig, params: Params) -> dict[str, np.ndarray]:
    """Inverse mapping (export): our pytree -> HF-named numpy state dict.
    Round-trip tested; lets checkpoints trained here load into transformers."""
    sd: dict[str, np.ndarray] = {
        "model.embed_tokens.weight": np.asarray(params["tok_embed"], np.float32),
        "model.norm.weight": np.asarray(params["final_norm"], np.float32),
    }
    if not cfg.tie_embeddings:
        sd["lm_head.weight"] = np.asarray(params["lm_head"], np.float32).T

    def put(i: int, name: str, val: np.ndarray):
        sd[f"model.layers.{i}.{name}"] = val

    kpre = cfg.n_dense_prefix
    for gi in range(cfg.n_layers):
        # dense-prefix layers export from their own stack under the
        # GLOBAL layer index; cfg view switches the MLP naming with them
        if kpre and gi < kpre:
            lp, i, cfg_i = params["prefix_layers"], gi, cfg.prefix_cfg()
        else:
            lp, i, cfg_i = params["layers"], gi - kpre, cfg.main_cfg()
        put(gi, "input_layernorm.weight", np.asarray(lp["attn_norm"][i], np.float32))
        if cfg_i.post_norms:
            put(gi, "post_attention_layernorm.weight",
                np.asarray(lp["attn_post_norm"][i], np.float32))
            put(gi, "pre_feedforward_layernorm.weight",
                np.asarray(lp["mlp_norm"][i], np.float32))
            put(gi, "post_feedforward_layernorm.weight",
                np.asarray(lp["mlp_post_norm"][i], np.float32))
        else:
            put(gi, "post_attention_layernorm.weight",
                np.asarray(lp["mlp_norm"][i], np.float32))
        if cfg_i.is_mla:
            hd, dr, r = cfg_i.head_dim_, cfg_i.mla_rope_dim, cfg_i.mla_latent_dim
            hn = cfg_i.n_heads
            if cfg_i.mla_q_lora_rank is not None:
                put(gi, "self_attn.q_a_proj.weight",
                    np.asarray(lp["w_qa"][i], np.float32).T)
                put(gi, "self_attn.q_a_layernorm.weight",
                    np.asarray(lp["q_a_norm"][i], np.float32))
                qb = np.asarray(lp["w_qb"][i], np.float32).reshape(
                    -1, hn, hd + dr)
                put(gi, "self_attn.q_b_proj.weight",
                    _rope_reinterleave(qb, dr).reshape(qb.shape[0], -1).T)
            else:
                q = np.asarray(lp["wq"][i], np.float32).reshape(
                    -1, hn, hd + dr)
                put(gi, "self_attn.q_proj.weight",
                    _rope_reinterleave(q, dr).reshape(q.shape[0], -1).T)
            put(gi, "self_attn.kv_a_proj_with_mqa.weight",
                _rope_reinterleave(
                    np.asarray(lp["w_dkv"][i], np.float32), dr).T)
            put(gi, "self_attn.kv_a_layernorm.weight",
                np.asarray(lp["c_norm"][i], np.float32))
            uk = np.asarray(lp["w_uk"][i], np.float32).reshape(r, hn, hd)
            uv = np.asarray(lp["w_uv"][i], np.float32).reshape(r, hn, hd)
            put(gi, "self_attn.kv_b_proj.weight",
                np.concatenate([uk, uv], axis=-1).reshape(r, -1).T)
            put(gi, "self_attn.o_proj.weight",
                np.asarray(lp["wo"][i], np.float32).T)
        else:
            for ours, theirs in (("wq", "self_attn.q_proj.weight"),
                                 ("wk", "self_attn.k_proj.weight"),
                                 ("wv", "self_attn.v_proj.weight"),
                                 ("wo", "self_attn.o_proj.weight")):
                put(gi, theirs, np.asarray(lp[ours][i], np.float32).T)
        if cfg_i.qk_norm:
            put(gi, "self_attn.q_norm.weight",
                np.asarray(lp["q_norm"][i], np.float32))
            put(gi, "self_attn.k_norm.weight",
                np.asarray(lp["k_norm"][i], np.float32))
        if cfg_i.qkv_bias:
            for ours, theirs in (("wq_b", "self_attn.q_proj.bias"),
                                 ("wk_b", "self_attn.k_proj.bias"),
                                 ("wv_b", "self_attn.v_proj.bias")):
                put(gi, theirs, np.asarray(lp[ours][i], np.float32))
        if cfg_i.n_experts:
            # family discriminates the naming (the SAME signal import
            # uses): MLA => DeepSeek-MoE names, else Mixtral names — a
            # chimera of MLA attention + block_sparse_moe would load
            # into neither transformers architecture
            if cfg_i.is_mla:
                put(gi, "mlp.gate.weight",
                    np.asarray(lp["router"][i], np.float32).T)
                if cfg_i.router_sigmoid_bias:
                    put(gi, "mlp.gate.e_score_correction_bias",
                        np.asarray(lp["router_bias"][i], np.float32))
                for e in range(cfg_i.n_experts):
                    put(gi, f"mlp.experts.{e}.gate_proj.weight",
                        np.asarray(lp["we_gate"][i, e], np.float32).T)
                    put(gi, f"mlp.experts.{e}.up_proj.weight",
                        np.asarray(lp["we_up"][i, e], np.float32).T)
                    put(gi, f"mlp.experts.{e}.down_proj.weight",
                        np.asarray(lp["we_down"][i, e], np.float32).T)
                if cfg_i.n_shared_experts:
                    put(gi, "mlp.shared_experts.gate_proj.weight",
                        np.asarray(lp["ws_gate"][i], np.float32).T)
                    put(gi, "mlp.shared_experts.up_proj.weight",
                        np.asarray(lp["ws_up"][i], np.float32).T)
                    put(gi, "mlp.shared_experts.down_proj.weight",
                        np.asarray(lp["ws_down"][i], np.float32).T)
            else:
                put(gi, "block_sparse_moe.gate.weight",
                    np.asarray(lp["router"][i], np.float32).T)
                for e in range(cfg_i.n_experts):
                    put(gi, f"block_sparse_moe.experts.{e}.w1.weight",
                        np.asarray(lp["we_gate"][i, e], np.float32).T)
                    put(gi, f"block_sparse_moe.experts.{e}.w3.weight",
                        np.asarray(lp["we_up"][i, e], np.float32).T)
                    put(gi, f"block_sparse_moe.experts.{e}.w2.weight",
                        np.asarray(lp["we_down"][i, e], np.float32).T)
        else:
            put(gi, "mlp.gate_proj.weight", np.asarray(lp["w_gate"][i], np.float32).T)
            put(gi, "mlp.up_proj.weight", np.asarray(lp["w_up"][i], np.float32).T)
            put(gi, "mlp.down_proj.weight", np.asarray(lp["w_down"][i], np.float32).T)
    return sd


def _read_dir_state_dict(path: str) -> dict[str, Any]:
    """Read a HF model directory: *.safetensors (indexed or single) or
    pytorch_model*.bin shards."""
    st_files = sorted(f for f in os.listdir(path) if f.endswith(".safetensors"))
    if st_files:
        from safetensors import safe_open
        sd: dict[str, Any] = {}
        index = os.path.join(path, "model.safetensors.index.json")
        if os.path.exists(index):
            with open(index) as f:
                weight_map = json.load(f)["weight_map"]
            st_files = sorted(set(weight_map.values()))
        for fname in st_files:
            with safe_open(os.path.join(path, fname), framework="np") as f:
                for k in f.keys():
                    sd[k] = f.get_tensor(k)
        return sd
    bin_files = sorted(f for f in os.listdir(path)
                       if re.match(r"pytorch_model.*\.bin$", f))
    if bin_files:
        import torch
        sd = {}
        for fname in bin_files:
            sd.update(torch.load(os.path.join(path, fname),
                                 map_location="cpu", weights_only=True))
        return sd
    raise FileNotFoundError(
        f"{path}: no *.safetensors or pytorch_model*.bin found")


def load_hf(cfg: LlamaConfig,
            src: Union[str, Mapping[str, Any]],
            dtype: Optional[Any] = None) -> Params:
    """One-call import: ``src`` is a HF model directory path, a state dict,
    or a transformers model object."""
    if hasattr(src, "state_dict"):
        src = src.state_dict()
    if isinstance(src, str):
        # MLA rejections (q_lora_rank, dense-prefix layers) fire on KEY
        # NAMES read from safetensors metadata — before materializing a
        # checkpoint that can be hundreds of GB
        names = _dir_key_names(src)
        if names is not None:
            _check_mla_keys(cfg, names)
        src = _read_dir_state_dict(src)
    return from_hf_state_dict(cfg, src, dtype=dtype)


def _dir_key_names(path: str) -> Optional[list[str]]:
    """Tensor names in a HF model dir from safetensors METADATA only
    (f.keys() never reads tensor data); None when only .bin shards exist
    (torch.load has no cheap header probe — the post-read check covers
    those)."""
    try:
        st_files = sorted(f for f in os.listdir(path)
                          if f.endswith(".safetensors"))
    except OSError:
        return None
    if not st_files:
        return None
    from safetensors import safe_open
    names: list[str] = []
    for fname in st_files:
        with safe_open(os.path.join(path, fname), framework="np") as f:
            names.extend(f.keys())
    return names
