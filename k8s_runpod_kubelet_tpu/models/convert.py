"""HuggingFace checkpoint import: load Llama/Qwen2/Gemma/Mixtral weights into
this framework's param pytree.

The reference framework ships opaque containers, so its users bring their own
weights; ours are typically published in HF format. This converter makes the
switch one call: ``params = load_hf(cfg, path_or_state_dict)``. Correctness is
proven the strong way in tests/test_hf_convert.py — logits parity against the
``transformers`` reference implementation on randomly-initialized tiny models
of every supported family (which also pins down our architecture fidelity:
RoPE convention, GQA layout, norm placement, activation, MoE routing).

Mapping notes:
- HF ``nn.Linear`` stores (out, in); our matmuls are x @ W with (in, out) —
  every projection transposes.
- Our layer leaves are STACKED with a leading (n_layers, ...) axis (the
  forward is one lax.scan over layers), so per-layer HF tensors stack.
- RoPE: both sides use the rotate-half pairing, so q/k convert untouched.
- Gemma: HF stores RMSNorm weights zero-centered (applied as 1+w) and scales
  embeddings by sqrt(E) in forward — both match cfg flags, no weight munging.
- Mixtral: experts e.w1/w3/w2 are gate/up/down; the router is ``gate``.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Mapping, Optional, Union

import numpy as np

from .llama import LlamaConfig, Params

__all__ = ["from_hf_state_dict", "load_hf", "to_hf_state_dict"]


def _np(t, dt: np.dtype) -> np.ndarray:
    """torch tensor / np array -> numpy in the TARGET dtype. Casting per
    tensor at read time (instead of a whole-tree f32 pass at the end) bounds
    peak host RAM to checkpoint + converted tree + ONE transient tensor —
    a 70B bf16 checkpoint converts without a ~4x f32 blowup."""
    if hasattr(t, "detach"):  # torch.Tensor without importing torch
        t = t.detach().cpu()
        if str(t.dtype) in ("torch.bfloat16", "torch.float16"):
            t = t.float()  # transient f32, this one tensor only
        t = t.numpy()
    return np.asarray(t).astype(dt, copy=False)


def _stack(sd: Mapping[str, Any], fmt: str, n_layers: int, dt: np.dtype,
           transpose: bool = False) -> np.ndarray:
    outs = []
    for i in range(n_layers):
        name = fmt.format(i=i)
        if name not in sd:
            raise KeyError(f"HF checkpoint missing {name!r}")
        w = _np(sd[name], dt)
        outs.append(w.T if transpose else w)
    return np.stack(outs)


def from_hf_state_dict(cfg: LlamaConfig, sd: Mapping[str, Any],
                       dtype: Optional[Any] = None) -> Params:
    """Map a HF ``model.state_dict()``-shaped mapping onto our param tree.

    Handles the ``model.`` prefix being present or absent. ``dtype`` defaults
    to cfg.param_dtype. Leaves come back as HOST (numpy) arrays — committing
    them to devices is the caller's job (device_put with its shardings), so a
    model bigger than one chip's HBM never materializes on device 0 first.
    """

    # normalize: strip a leading "model." so both full-model and bare
    # state dicts work; keep lm_head at top level
    norm: dict[str, Any] = {}
    for k, v in sd.items():
        norm[k[len("model."):] if k.startswith("model.") else k] = v
    sd = norm
    L = cfg.n_layers
    dt = np.dtype(dtype or cfg.param_dtype)  # jnp.bfloat16 works via ml_dtypes
    pre = "layers.{i}."

    layers: dict[str, np.ndarray] = {
        "attn_norm": _stack(sd, pre + "input_layernorm.weight", L, dt),
        "wq": _stack(sd, pre + "self_attn.q_proj.weight", L, dt, transpose=True),
        "wk": _stack(sd, pre + "self_attn.k_proj.weight", L, dt, transpose=True),
        "wv": _stack(sd, pre + "self_attn.v_proj.weight", L, dt, transpose=True),
        "wo": _stack(sd, pre + "self_attn.o_proj.weight", L, dt, transpose=True),
    }
    if cfg.post_norms:
        # Gemma-2 sandwich norms: HF's post_attention_layernorm is the
        # POST-attention output norm; the pre-MLP norm is
        # pre_feedforward_layernorm
        layers["attn_post_norm"] = _stack(
            sd, pre + "post_attention_layernorm.weight", L, dt)
        layers["mlp_norm"] = _stack(
            sd, pre + "pre_feedforward_layernorm.weight", L, dt)
        layers["mlp_post_norm"] = _stack(
            sd, pre + "post_feedforward_layernorm.weight", L, dt)
    else:
        layers["mlp_norm"] = _stack(
            sd, pre + "post_attention_layernorm.weight", L, dt)
    if cfg.qk_norm:
        layers["q_norm"] = _stack(sd, pre + "self_attn.q_norm.weight", L, dt)
        layers["k_norm"] = _stack(sd, pre + "self_attn.k_norm.weight", L, dt)
    if cfg.qkv_bias:
        layers["wq_b"] = _stack(sd, pre + "self_attn.q_proj.bias", L, dt)
        layers["wk_b"] = _stack(sd, pre + "self_attn.k_proj.bias", L, dt)
        layers["wv_b"] = _stack(sd, pre + "self_attn.v_proj.bias", L, dt)
    if cfg.n_experts:
        layers["router"] = _stack(
            sd, pre + "block_sparse_moe.gate.weight", L, dt, transpose=True)
        gates, ups, downs = [], [], []
        for i in range(L):
            g = [_np(sd[f"layers.{i}.block_sparse_moe.experts.{e}.w1.weight"], dt).T
                 for e in range(cfg.n_experts)]
            u = [_np(sd[f"layers.{i}.block_sparse_moe.experts.{e}.w3.weight"], dt).T
                 for e in range(cfg.n_experts)]
            d = [_np(sd[f"layers.{i}.block_sparse_moe.experts.{e}.w2.weight"], dt).T
                 for e in range(cfg.n_experts)]
            gates.append(np.stack(g))
            ups.append(np.stack(u))
            downs.append(np.stack(d))
        layers["we_gate"] = np.stack(gates)
        layers["we_up"] = np.stack(ups)
        layers["we_down"] = np.stack(downs)
    else:
        layers["w_gate"] = _stack(sd, pre + "mlp.gate_proj.weight", L, dt,
                                  transpose=True)
        layers["w_up"] = _stack(sd, pre + "mlp.up_proj.weight", L, dt,
                                transpose=True)
        layers["w_down"] = _stack(sd, pre + "mlp.down_proj.weight", L, dt,
                                  transpose=True)

    params: Params = {
        "tok_embed": _np(sd["embed_tokens.weight"], dt),
        "final_norm": _np(sd["norm.weight"], dt),
        "layers": layers,
    }
    if not cfg.tie_embeddings:
        if "lm_head.weight" in sd:
            params["lm_head"] = _np(sd["lm_head.weight"], dt).T
        else:  # checkpoint ties but config doesn't: materialize the tie
            params["lm_head"] = params["tok_embed"].T.copy()
    return params


def to_hf_state_dict(cfg: LlamaConfig, params: Params) -> dict[str, np.ndarray]:
    """Inverse mapping (export): our pytree -> HF-named numpy state dict.
    Round-trip tested; lets checkpoints trained here load into transformers."""
    sd: dict[str, np.ndarray] = {
        "model.embed_tokens.weight": np.asarray(params["tok_embed"], np.float32),
        "model.norm.weight": np.asarray(params["final_norm"], np.float32),
    }
    if not cfg.tie_embeddings:
        sd["lm_head.weight"] = np.asarray(params["lm_head"], np.float32).T
    lp = params["layers"]

    def put(i: int, name: str, val: np.ndarray):
        sd[f"model.layers.{i}.{name}"] = val

    for i in range(cfg.n_layers):
        put(i, "input_layernorm.weight", np.asarray(lp["attn_norm"][i], np.float32))
        if cfg.post_norms:
            put(i, "post_attention_layernorm.weight",
                np.asarray(lp["attn_post_norm"][i], np.float32))
            put(i, "pre_feedforward_layernorm.weight",
                np.asarray(lp["mlp_norm"][i], np.float32))
            put(i, "post_feedforward_layernorm.weight",
                np.asarray(lp["mlp_post_norm"][i], np.float32))
        else:
            put(i, "post_attention_layernorm.weight",
                np.asarray(lp["mlp_norm"][i], np.float32))
        for ours, theirs in (("wq", "self_attn.q_proj.weight"),
                             ("wk", "self_attn.k_proj.weight"),
                             ("wv", "self_attn.v_proj.weight"),
                             ("wo", "self_attn.o_proj.weight")):
            put(i, theirs, np.asarray(lp[ours][i], np.float32).T)
        if cfg.qk_norm:
            put(i, "self_attn.q_norm.weight",
                np.asarray(lp["q_norm"][i], np.float32))
            put(i, "self_attn.k_norm.weight",
                np.asarray(lp["k_norm"][i], np.float32))
        if cfg.qkv_bias:
            for ours, theirs in (("wq_b", "self_attn.q_proj.bias"),
                                 ("wk_b", "self_attn.k_proj.bias"),
                                 ("wv_b", "self_attn.v_proj.bias")):
                put(i, theirs, np.asarray(lp[ours][i], np.float32))
        if cfg.n_experts:
            put(i, "block_sparse_moe.gate.weight",
                np.asarray(lp["router"][i], np.float32).T)
            for e in range(cfg.n_experts):
                put(i, f"block_sparse_moe.experts.{e}.w1.weight",
                    np.asarray(lp["we_gate"][i, e], np.float32).T)
                put(i, f"block_sparse_moe.experts.{e}.w3.weight",
                    np.asarray(lp["we_up"][i, e], np.float32).T)
                put(i, f"block_sparse_moe.experts.{e}.w2.weight",
                    np.asarray(lp["we_down"][i, e], np.float32).T)
        else:
            put(i, "mlp.gate_proj.weight", np.asarray(lp["w_gate"][i], np.float32).T)
            put(i, "mlp.up_proj.weight", np.asarray(lp["w_up"][i], np.float32).T)
            put(i, "mlp.down_proj.weight", np.asarray(lp["w_down"][i], np.float32).T)
    return sd


def _read_dir_state_dict(path: str) -> dict[str, Any]:
    """Read a HF model directory: *.safetensors (indexed or single) or
    pytorch_model*.bin shards."""
    st_files = sorted(f for f in os.listdir(path) if f.endswith(".safetensors"))
    if st_files:
        from safetensors import safe_open
        sd: dict[str, Any] = {}
        index = os.path.join(path, "model.safetensors.index.json")
        if os.path.exists(index):
            with open(index) as f:
                weight_map = json.load(f)["weight_map"]
            st_files = sorted(set(weight_map.values()))
        for fname in st_files:
            with safe_open(os.path.join(path, fname), framework="np") as f:
                for k in f.keys():
                    sd[k] = f.get_tensor(k)
        return sd
    bin_files = sorted(f for f in os.listdir(path)
                       if re.match(r"pytorch_model.*\.bin$", f))
    if bin_files:
        import torch
        sd = {}
        for fname in bin_files:
            sd.update(torch.load(os.path.join(path, fname),
                                 map_location="cpu", weights_only=True))
        return sd
    raise FileNotFoundError(
        f"{path}: no *.safetensors or pytorch_model*.bin found")


def load_hf(cfg: LlamaConfig,
            src: Union[str, Mapping[str, Any]],
            dtype: Optional[Any] = None) -> Params:
    """One-call import: ``src`` is a HF model directory path, a state dict,
    or a transformers model object."""
    if cfg.is_mla:
        # fail BEFORE reading a ~16B checkpoint: the mapping below stacks
        # self_attn.{k,v}_proj which DeepSeek-V2 checkpoints don't have
        # (they ship kv_a_proj_with_mqa/kv_b_proj for w_dkv/w_uk/w_uv)
        raise NotImplementedError(
            f"HF checkpoint import has no MLA weight mapping yet "
            f"({cfg.name}: w_dkv/w_uk/w_uv); init randomly or convert "
            "offline")
    if hasattr(src, "state_dict"):
        src = src.state_dict()
    if isinstance(src, str):
        src = _read_dir_state_dict(src)
    return from_hf_state_dict(cfg, src, dtype=dtype)
