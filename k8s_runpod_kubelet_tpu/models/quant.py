"""Weight-only int8 quantization for serving (JetStream/MaxText parity).

Decode is HBM-bandwidth-bound: every step streams the full weight set through
VMEM for a handful of tokens, so halving weight bytes (bf16 -> int8) is worth
~2x decode throughput before any accuracy consideration. This is symmetric
per-output-channel absmax quantization:

    q8    = round(w / scale), int8
    scale = absmax(w, contraction_axis) / 127          (f32, kept per channel)
    y     = (x @ q8.astype(bf16)) * scale              (dequant fused by XLA)

The dequant multiply rides the matmul epilogue — XLA fuses it, so the HBM
read is int8 and the MXU still sees its native dtype. Activations stay bf16
(weight-only): no calibration pass needed, and decode logits stay within
argmax-stable tolerance of the bf16 path (tests/test_quant.py).

A quantized weight is a dict leaf ``{"q8": int8 (..., in, out),
"scale": f32 (..., 1, out)}``; the model's matmul helper (llama._mm) accepts
either form, so train/serve code paths are unchanged. Norms, biases, the
embedding table (gather path + possible tied head), and the MoE router stay
full precision — they are tiny and accuracy-critical. Sparse-MoE EXPERT
weights quantize at BOTH widths (moe._expert_matmul applies the scale in
the expert matmul's epilogue; Mixtral's experts are ~96% of its params, so
weight-only quantization on an MoE model lives or dies on them): int8 rides
the einsum, int4 goes per-expert through the ops/int4_matmul.py unpack
kernel (int4_expert_matmul), group-wise scales along each expert's
contraction axis exactly like the dense leaves.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from .llama import LlamaConfig, Params

__all__ = ["quantize_params", "is_quantized", "quantized_logical_axes"]

# stacked-layer projection weights with (in, out) as the trailing dims,
# plus the top-level lm head — the decode-bandwidth heavy hitters.
# MLA: w_dkv and the shared-expert MLP quantize (plain _mm consumers);
# w_uk/w_uv stay full precision — the absorbed decode path consumes them
# via reshape+einsum (not _mm), and at (r, H*dh) they are tiny next to
# the latent-cache reads the absorbed form exists to shrink.
_LAYER_WEIGHTS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
                  "w_dkv", "ws_gate", "ws_up", "ws_down", "w_qa", "w_qb")
# expert weights: {q8, scale} rides moe.py's einsums; int4 {q4, scale}
# goes per-expert through the 2D unpack kernel (int4_expert_matmul)
_EXPERT_WEIGHTS = ("we_gate", "we_up", "we_down")


def _quantize_leaf(w) -> dict[str, np.ndarray]:
    # quantize on HOST (numpy): a stacked llama3-8b w_gate upcast to f32 on
    # device would transiently cost ~7.5GB HBM; this way the device only
    # ever sees the int8 weights + f32 scales. Leaves stay NUMPY here —
    # quantize_params commits them (or the caller device_puts them under
    # shardings; a 70B leaf must never land whole on one device).
    w = np.asarray(w, np.float32)
    scale = np.max(np.abs(w), axis=-2, keepdims=True) / 127.0
    scale = np.maximum(scale, 1e-8)
    q8 = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
    return {"q8": q8, "scale": scale}


INT4_GROUP = 128  # contraction-axis group size for int4 scales


def _quantize_leaf_int4(w, group_size: int = INT4_GROUP) -> dict[str, jax.Array]:
    """Symmetric int4 ([-7, 7], stored offset-by-8 in a nibble) with
    GROUP-WISE absmax scales along the contraction axis — per-channel alone
    is too coarse at 4 bits (one outlier wipes a whole column's resolution;
    128-wide groups bound the blast radius and match the MXU's native
    contraction depth). Two values pack per uint8: in-axis element 2i rides
    the low nibble, 2i+1 the high — weight bytes drop 4x vs bf16."""
    w = np.asarray(w, np.float32)
    kin, out = w.shape[-2], w.shape[-1]
    assert kin % 2 == 0, f"int4 packing needs an even contraction dim, got {kin}"
    gs = group_size if kin % group_size == 0 else kin
    g = kin // gs
    wr = w.reshape(*w.shape[:-2], g, gs, out)
    scale = np.max(np.abs(wr), axis=-2, keepdims=True) / 7.0  # (..., g, 1, out)
    scale = np.maximum(scale, 1e-8)
    q = np.clip(np.round(wr / scale), -7, 7).astype(np.int8) + 8  # 1..15
    q = q.reshape(*w.shape[:-2], kin, out).astype(np.uint8)
    packed = (q[..., 0::2, :] | (q[..., 1::2, :] << 4)).astype(np.uint8)
    return {"q4": packed, "scale": scale}


def is_quantized(w: Any) -> bool:
    return isinstance(w, dict) and ("q8" in w or "q4" in w)


def quantized_logical_axes(cfg: LlamaConfig, bits: int = 8) -> Params:
    """Logical-axis tree for a quantized param tree (mirrors
    quantize_params output), so 70B-class quantized serving can shard over
    a mesh exactly like bf16 serving.

    bits=8: ``q8`` keeps the base weight's axes; ``scale`` (..., 1, out)
    replicates its singleton contraction dim and keeps the output axis.

    bits=4: every packed weight shards its OUTPUT axis over the dedicated
    ``int4_out`` logical axis (-> tensor) and replicates the packed
    contraction + group axes — the layout ops/int4_matmul.py's
    int4_matmul_sharded (shard_map) partitions the Pallas kernel under. (The
    contraction axis CANNOT shard: it is 2x-packed and 128-grouped, so a
    propagated shard on the activation axis has no consistent image on
    the byte/group axes; out-sharding keeps every weight distributed and
    only the KB-scale activations replicate.)"""
    from .llama import param_logical_axes
    base = param_logical_axes(cfg)

    if bits == 4:
        def q_axes(axes):
            lead = axes[:-2]   # ("layer",) for stacked weights, () for lm_head
            if "expert" in lead:
                # expert leaves shard their EXPERT axis only: the packed
                # contraction axis cannot shard (2x-packed + grouped), and
                # out-sharding over tensor would force an all-gather
                # before the MoE combine — EP is the int4 experts' memory
                # lever (moe._expert_ffn_sharded's layout contract)
                return {"q4": lead + (None, None),
                        "scale": lead + (None, None, None)}
            return {"q4": lead + (None, "int4_out"),
                    "scale": lead + (None, None, "int4_out")}
    else:
        def q_axes(axes):
            return {"q8": axes, "scale": axes[:-2] + (None, axes[-1])}

    quantized = set(_LAYER_WEIGHTS) | set(_EXPERT_WEIGHTS)

    out: Params = {"tok_embed": base["tok_embed"],
                   "final_norm": base["final_norm"]}
    for stack in ("layers", "prefix_layers"):
        if stack in base:
            out[stack] = {
                name: (q_axes(axes) if name in quantized else axes)
                for name, axes in base[stack].items()
            }
    if "lm_head" in base:
        out["lm_head"] = q_axes(base["lm_head"])
    return out


def quantize_params(cfg: LlamaConfig, params: Params,
                    bits: int = 8, commit: bool = True) -> Params:
    """Returns a new tree with projection weights int8- or int4-quantized.
    Accepts host (numpy) or device trees; output leaves are device arrays.
    The embedding table (unquantized: gathers don't amortize dequant the
    way matmuls do) is stored in the COMPUTE dtype — llama3-8b's f32 table
    is 2.1GB of the 16GB v5e, bf16 halves it with no extra loss: the
    embedding's first use is already a cast-to-bf16 matmul input. Norms
    stay f32 (tiny, precision-sensitive). ``bits=4`` packs two weights per
    byte with group-wise scales (_quantize_leaf_int4) — weight HBM drops
    4x vs bf16, the next rung of the decode-bandwidth ladder.

    ``commit=False`` returns HOST (numpy) leaves: mesh serving must
    device_put each leaf under its target sharding — a 70B stacked leaf
    committed whole to one device (what jnp.asarray does) is itself
    bigger than a v5e's HBM."""
    if bits not in (4, 8):
        raise ValueError(f"bits must be 4 or 8, got {bits}")
    quant = _quantize_leaf if bits == 8 else _quantize_leaf_int4
    place = jnp.asarray if commit else (lambda x, *a: np.asarray(x, *a))
    out: Params = {"tok_embed": place(params["tok_embed"],
                                      np.dtype(cfg.dtype) if not commit
                                      else cfg.dtype),
                   "final_norm": place(params["final_norm"])}
    for stack in ("layers", "prefix_layers"):
        if stack not in params:
            continue
        layers = {}
        for name, w in params[stack].items():
            if name in _LAYER_WEIGHTS or name in _EXPERT_WEIGHTS:
                leaf = quant(w)
                layers[name] = (jax.tree_util.tree_map(jnp.asarray, leaf)
                                if commit else leaf)
            elif name in ("w_uk", "w_uv"):
                # MLA up-projections: unquantized (absorbed decode consumes
                # them via reshape+einsum, not _mm) but stored in the
                # COMPUTE dtype — f32 would double their HBM reads
                layers[name] = place(w, np.dtype(cfg.dtype) if not commit
                                     else cfg.dtype)
            else:
                layers[name] = place(w)
        out[stack] = layers
    if "lm_head" in params:
        leaf = quant(params["lm_head"])
        out["lm_head"] = (jax.tree_util.tree_map(jnp.asarray, leaf)
                          if commit else leaf)
    return out
