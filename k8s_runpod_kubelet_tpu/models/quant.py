"""Weight-only int8 quantization for serving (JetStream/MaxText parity).

Decode is HBM-bandwidth-bound: every step streams the full weight set through
VMEM for a handful of tokens, so halving weight bytes (bf16 -> int8) is worth
~2x decode throughput before any accuracy consideration. This is symmetric
per-output-channel absmax quantization:

    q8    = round(w / scale), int8
    scale = absmax(w, contraction_axis) / 127          (f32, kept per channel)
    y     = (x @ q8.astype(bf16)) * scale              (dequant fused by XLA)

The dequant multiply rides the matmul epilogue — XLA fuses it, so the HBM
read is int8 and the MXU still sees its native dtype. Activations stay bf16
(weight-only): no calibration pass needed, and decode logits stay within
argmax-stable tolerance of the bf16 path (tests/test_quant.py).

A quantized weight is a dict leaf ``{"q8": int8 (..., in, out),
"scale": f32 (..., 1, out)}``; the model's matmul helper (llama._mm) accepts
either form, so train/serve code paths are unchanged. Norms, biases, the
embedding table (gather path + possible tied head), and the MoE router stay
full precision — they are tiny and accuracy-critical. Sparse-MoE expert
weights are left unquantized for now (einsum path).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from .llama import LlamaConfig, Params

__all__ = ["quantize_params", "is_quantized"]

# stacked-layer projection weights with (in, out) as the trailing dims,
# plus the top-level lm head — the decode-bandwidth heavy hitters
_LAYER_WEIGHTS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def _quantize_leaf(w) -> dict[str, jax.Array]:
    # quantize on HOST (numpy): a stacked llama3-8b w_gate upcast to f32 on
    # device would transiently cost ~7.5GB HBM; this way the device only
    # ever sees the int8 weights + f32 scales
    w = np.asarray(w, np.float32)
    scale = np.max(np.abs(w), axis=-2, keepdims=True) / 127.0
    scale = np.maximum(scale, 1e-8)
    q8 = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
    return {"q8": jnp.asarray(q8), "scale": jnp.asarray(scale)}


def is_quantized(w: Any) -> bool:
    return isinstance(w, dict) and "q8" in w


def quantize_params(cfg: LlamaConfig, params: Params) -> Params:
    """Returns a new tree with projection weights int8-quantized.
    Accepts host (numpy) or device trees; output leaves are device arrays.
    The embedding table (unquantized: gathers don't amortize dequant the
    way matmuls do) is stored in the COMPUTE dtype — llama3-8b's f32 table
    is 2.1GB of the 16GB v5e, bf16 halves it with no extra loss: the
    embedding's first use is already a cast-to-bf16 matmul input. Norms
    stay f32 (tiny, precision-sensitive)."""
    out: Params = {"tok_embed": jnp.asarray(params["tok_embed"], cfg.dtype),
                   "final_norm": jnp.asarray(params["final_norm"])}
    layers = {}
    for name, w in params["layers"].items():
        if name in _LAYER_WEIGHTS:
            layers[name] = _quantize_leaf(w)
        else:
            layers[name] = jnp.asarray(w)
    out["layers"] = layers
    if "lm_head" in params:
        out["lm_head"] = _quantize_leaf(params["lm_head"])
    return out
