"""Model zoo: the north-star workloads (BASELINE.json configs 2-5).

- ``llama``: Llama-3-family decoder (flagship; 8B pretrain = config 3,
  70B multislice = config 4) with GQA, RoPE, flash/ring attention, KV-cache
  decode, and logical-axis sharding throughout.
- ``gemma``: Gemma-7B config mapped onto the same decoder (serving = config 5).
- ``mixtral``: Mixtral-8x7B sparse-MoE config on the same decoder, routed
  through the expert-parallel MoE MLP (``moe``).
- ``mnist``: the small Flax CNN for the single-chip smoke workload (config 2).
- ``convert``: HuggingFace checkpoint import/export (``load_hf``), logits-
  parity-tested against ``transformers`` for every family.
"""

from .llama import (LlamaConfig, LlamaModel, llama3_8b, llama3_70b, llama31_8b, gemma_7b,
                    gemma2_9b, gemma3_12b, mixtral_8x7b, mistral_7b, qwen2_7b, qwen3_8b,
                    deepseek_v2_lite, deepseek_v3, mla_8b, tiny_llama, tiny_moe, tiny_mla, init_params, param_logical_axes)
from .mnist import MnistCNN, mnist_config
from .moe import moe_mlp, moe_mlp_dense_reference, moe_capacity
from .convert import load_hf, from_hf_state_dict, to_hf_state_dict
from .quant import quantize_params, is_quantized
from .lora import LoraConfig, apply_lora, merge_lora, lora_mask, lora_param_count

# One name-keyed registry consumed by BOTH CLIs (serve_main/train_main)
# for argparse choices AND dispatch — adding a model is one entry here,
# not six coordinated edits across three files.
MODEL_CONFIGS = {
    "llama3-8b": llama3_8b, "llama3-70b": llama3_70b,
    "llama31-8b": llama31_8b,
    "gemma-7b": gemma_7b, "gemma2-9b": gemma2_9b, "gemma3-12b": gemma3_12b,
    "mixtral-8x7b": mixtral_8x7b, "mistral-7b": mistral_7b,
    "qwen2-7b": qwen2_7b, "qwen3-8b": qwen3_8b,
    "deepseek-v2-lite": deepseek_v2_lite, "deepseek-v3": deepseek_v3,
    "tiny": tiny_llama, "tiny-moe": tiny_moe, "tiny-mla": tiny_mla,
}

__all__ = ["LlamaConfig", "LlamaModel", "llama3_8b", "llama3_70b", "llama31_8b", "gemma_7b",
           "gemma2_9b", "gemma3_12b", "mixtral_8x7b", "mistral_7b", "qwen2_7b", "qwen3_8b",
           "deepseek_v2_lite", "deepseek_v3", "mla_8b", "tiny_llama", "tiny_moe", "tiny_mla", "MODEL_CONFIGS", "init_params",
           "param_logical_axes", "MnistCNN", "mnist_config", "moe_mlp",
           "moe_mlp_dense_reference", "moe_capacity", "load_hf",
           "from_hf_state_dict", "to_hf_state_dict", "quantize_params",
           "is_quantized", "LoraConfig", "apply_lora", "merge_lora",
           "lora_mask", "lora_param_count"]
