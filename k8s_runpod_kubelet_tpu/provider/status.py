"""QueuedResource state + gang runtime -> v1.PodStatus translation.

Rebuild of translateRunPodStatus + checkPortsExposed + handlePodCompletion
(kubelet.go:566-605, 976-1065, 1798-2024), re-thought for slices:

- the reference's "RUNNING but ports not yet exposed => still Pending"
  readiness heuristic generalizes to "slice ACTIVE but the gang isn't fully
  running => still Pending" (SURVEY.md §7.4 hard-part #6);
- EXITED message-sniffing (kubelet.go:1903-1926) becomes exact per-worker exit
  codes, aggregated all-or-nothing;
- a single unhealthy worker fails the WHOLE pod (gang-fail, SURVEY.md §5.3) —
  preemption is a normal event on TPUs, and the Job controller is the retry
  mechanism;
- the pod IP is worker 0's real address, not a placeholder
  (kubelet.go:2016-2017 used 10.0.0.1).
"""

from __future__ import annotations

import logging
from typing import Optional

from ..cloud.types import DetailedStatus, QueuedResourceState as S
from ..kube import objects as ko
from .translate import HTTP_PORTS

log = logging.getLogger(__name__)


def check_ports_exposed(requested_ports: list[str], detailed: DetailedStatus) -> bool:
    """Port-readiness parity (kubelet.go:566-605): HTTP-ish ports are assumed
    ready; TCP ports must appear in the slice's port mappings."""
    for p in requested_ports:
        try:
            port_s, _, proto = p.partition("/")
            port = int(port_s)
        except ValueError:
            continue
        if proto.lower() == "udp" or port in HTTP_PORTS:
            continue
        if port not in detailed.ports:
            return False
    return True


def gang_ready(detailed: DetailedStatus) -> bool:
    """The TPU readiness condition: every worker healthy and running the
    workload. This is what 'ICI mesh can form' means from the control plane."""
    return (detailed.all_workers_healthy
            and bool(detailed.runtime)
            and all(w.workload_running or w.exit_code is not None
                    for w in detailed.runtime))


def _container_name(pod: dict) -> str:
    cs = ko.containers(pod)
    return cs[0].get("name", "workload") if cs else "workload"


def _base(pod: dict, phase: str, reason: str = "", message: str = "",
          ready: bool = False, pod_ip: str = "", start_time: Optional[str] = None,
          container_state: Optional[dict] = None,
          container_ready: bool = False, restart_count: int = 0) -> dict:
    conditions = [
        {"type": "PodScheduled", "status": "True"},
        {"type": "Initialized", "status": "True"},
        {"type": "Ready", "status": "True" if ready else "False"},
        {"type": "ContainersReady", "status": "True" if ready else "False"},
    ]
    status: dict = {"phase": phase, "conditions": conditions}
    if reason:
        status["reason"] = reason
    if message:
        status["message"] = message
    if pod_ip:
        status["podIP"] = pod_ip
        status["podIPs"] = [{"ip": pod_ip}]
    if start_time:
        status["startTime"] = start_time
    if container_state is not None:
        status["containerStatuses"] = [{
            "name": _container_name(pod),
            "state": container_state,
            "ready": container_ready,
            "restartCount": restart_count,
            "image": (ko.containers(pod)[0].get("image", "") if ko.containers(pod) else ""),
            "imageID": "",
            "containerID": "",
        }]
    return status


def translate_status(pod: dict, detailed: DetailedStatus, *,
                     workload_launched: bool,
                     ports_exposed: Optional[bool] = None) -> dict:
    """Main translation (parity: translateRunPodStatus kubelet.go:1848-2024)."""
    qr = detailed.resource
    state = qr.state
    pod_ip = ""
    if qr.workers:
        pod_ip = qr.workers[0].internal_ip or ""
    if ports_exposed is None:
        ports_exposed = check_ports_exposed(
            [p for c in ko.containers(pod) for p in
             [f"{pp['containerPort']}/{pp.get('protocol', 'TCP').lower()}"
              for pp in c.get("ports", [])]],
            detailed)

    if state in (S.ACCEPTED, S.WAITING_FOR_RESOURCES):
        return _base(pod, "Pending", reason="SliceQueued",
                     message=f"queued resource {qr.name}: {qr.state_message or state.value}",
                     container_state={"waiting": {"reason": "SliceQueued",
                                                  "message": "waiting for TPU capacity"}})
    if state is S.PROVISIONING:
        return _base(pod, "Pending", reason="SliceProvisioning",
                     message=f"TPU VMs creating for {qr.name}",
                     container_state={"waiting": {"reason": "SliceProvisioning",
                                                  "message": "TPU VMs are being created"}})

    if state is S.ACTIVE:
        if detailed.all_exited:
            return completion_status(pod, detailed)
        if detailed.runtime and not detailed.all_workers_healthy:
            # gang broken: one dead worker fails the pod (SURVEY.md §5.3)
            bad = [w.worker_id for w in detailed.runtime if not w.healthy]
            return _base(pod, "Failed", reason="GangBroken",
                         message=f"workers {bad} unhealthy — slice gang broken; "
                                 "the owning controller should recreate the pod",
                         container_state={"terminated": {
                             "exitCode": 137, "reason": "GangBroken"}})
        if workload_launched and gang_ready(detailed) and ports_exposed:
            started = min((w.started_at for w in detailed.runtime
                           if w.started_at), default=None)
            return _base(pod, "Running", ready=True, pod_ip=pod_ip,
                         start_time=ko.now_iso(started),
                         container_state={"running": {"startedAt": ko.now_iso(started)}},
                         container_ready=True)
        # ACTIVE but gang not fully up — the reference's RUNNING-without-ports
        # => ContainerCreating case (kubelet.go:1867-1890)
        return _base(pod, "Pending", reason="ContainerCreating", pod_ip=pod_ip,
                     message="slice active; launching workload on all workers",
                     container_state={"waiting": {"reason": "ContainerCreating",
                                                  "message": "gang launch in progress"}})

    if state in (S.SUSPENDING, S.SUSPENDED):
        return _base(pod, "Failed", reason="Preempted",
                     message=f"slice {qr.name} preempted: {qr.state_message}",
                     container_state={"terminated": {"exitCode": 137,
                                                     "reason": "Preempted"}})
    if state is S.DELETING:
        # keep whatever phase the pod already had — DELETING is transitional
        # (the pod is usually being deleted anyway); never report Running for
        # a gang that may never have run, and never mark it ready
        prior = pod.get("status", {}).get("phase") or "Pending"
        if prior in ("Succeeded", "Failed"):
            prior_status = dict(pod["status"])
            return prior_status
        return _base(pod, prior, reason="SliceDeleting",
                     message=f"slice {qr.name} deleting", pod_ip=pod_ip,
                     container_state={"waiting": {"reason": "SliceDeleting"}})
    if state is S.FAILED:
        return _base(pod, "Failed", reason="SliceFailed",
                     message=f"slice {qr.name} failed: {qr.state_message}",
                     container_state={"terminated": {"exitCode": 1,
                                                     "reason": "SliceFailed"}})
    if state is S.NOT_FOUND:
        return _base(pod, "Failed", reason="SliceNotFound",
                     message=f"queued resource {qr.name} no longer exists "
                             "(parity: kubelet.go:1953-1965)",
                     container_state={"terminated": {"exitCode": 1,
                                                     "reason": "SliceNotFound"}})
    return _base(pod, "Unknown", reason="UnknownSliceState", message=str(state))


def completion_status(pod: dict, detailed: DetailedStatus) -> dict:
    """All workers exited -> Succeeded iff every exit code is 0 (parity:
    handlePodCompletion kubelet.go:998-1065 + IsSuccessfulCompletion
    runpod_client.go:821-843 — but with real per-worker exit codes instead of
    message sniffing)."""
    code = detailed.max_exit_code or 0
    ok = code == 0
    failed = {w.worker_id: w.exit_code for w in detailed.runtime
              if w.exit_code not in (None, 0)}
    finished = max((w.finished_at for w in detailed.runtime if w.finished_at),
                   default=None)
    msg = ("all workers completed successfully" if ok
           else f"worker exit codes: {failed}")
    return _base(pod, "Succeeded" if ok else "Failed",
                 reason="Completed" if ok else "WorkersFailed",
                 message=msg,
                 container_state={"terminated": {
                     "exitCode": code,
                     "reason": "Completed" if ok else "Error",
                     "message": msg,
                     "finishedAt": ko.now_iso(finished),
                 }})


def status_fingerprint(status: dict) -> tuple:
    """Change-detection key (parity: the reference patches only when status or
    port-exposure changed, kubelet.go:870-872)."""
    cs = status.get("containerStatuses") or [{}]
    state = cs[0].get("state", {})
    kind = next(iter(state), "")
    return (status.get("phase"), status.get("reason"),
            status.get("podIP", ""), kind,
            state.get(kind, {}).get("exitCode"),
            cs[0].get("ready"))
