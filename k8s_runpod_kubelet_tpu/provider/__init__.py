"""L2': Provider core — pod lifecycle, spec/status translation, reconcile loops.

The TPU-native rebuild of the reference's Provider
(/root/reference/pkg/virtual_kubelet/kubelet.go, 2,066 LoC). Split by concern:

- ``annotations``: tpu.dev/* annotation schema + pod>Job fallback resolution.
- ``translate``:   the pod -> slice-parameters compiler (env/secret extraction,
                   accelerator selection, ports).
- ``status``:      QueuedResource state + gang runtime -> v1.PodStatus.
- ``node_spec``:   the virtual Node object (google.com/tpu capacity, topology
                   labels, taint, conditions).
- ``provider``:    the Provider class (caches, lifecycle handlers, deploy).
- ``reconcile``:   steady-state loops (status poll, pending retry, GC ladder).
- ``recovery``:    crash recovery (LoadRunning 3-way reconcile, orphan adoption).
"""

from .provider import InstanceInfo, Provider
from .annotations import Annotations

__all__ = ["Provider", "InstanceInfo", "Annotations"]
