"""Pod spec -> TPU slice parameters: the K8s->cloud compiler.

TPU-native rebuild of PrepareRunPodParameters + ExtractEnvVars + port extraction
(runpod_client.go:845-1393). Deliberate improvements over the reference, per
SURVEY.md §7.2:

- env/secrets are read from ALL containers, not Containers[0] only
  (the reference's documented bug, runpod_client.go:1028-1030);
- the accelerator request (google.com/tpu) actually drives slice sizing —
  the reference never reads its GPU count (SURVEY.md §2.4);
- the cost ceiling is enforced (the reference's --max-gpu-price is dead,
  SURVEY.md §5.6);
- queued-resource names derive deterministically from the pod UID so crash
  recovery can re-map them by listing (SURVEY.md §5.4), and the slice carries
  pod identity labels for the reverse mapping.
"""

from __future__ import annotations

import base64
import logging
import re

from ..cloud.tpu_client import TpuParameters, WorkloadSpec
from ..cloud.types import AcceleratorType, lookup_accelerator, select_accelerator
from ..config import Config
from ..kube.client import KubeApiError, KubeClient
from ..kube import objects as ko
from .annotations import AnnotationResolver, Annotations as A

log = logging.getLogger(__name__)


class TranslationError(Exception):
    """Pod spec cannot be translated; the pod should stay Pending and retry."""


# Ports whose services speak HTTP — assumed ready without a mapping
# (readiness-heuristic parity: runpod_client.go:1199-1208).
HTTP_PORTS = {80, 443, 8080, 8000, 3000, 5000, 8888, 9000}

# K8s auto-injects these into every container; forwarding them to the cloud
# instance leaks cluster internals and breaks workloads
# (filter parity: runpod_client.go:886-904).
_AUTO_ENV_EXACT = {"KUBERNETES_SERVICE_HOST", "KUBERNETES_SERVICE_PORT",
                   "KUBERNETES_SERVICE_PORT_HTTPS", "KUBERNETES_PORT"}
_AUTO_ENV_RE = re.compile(r"^KUBERNETES_PORT_|_SERVICE_HOST$|_SERVICE_PORT$|_SERVICE_PORT_|_PORT_\d+_(TCP|UDP)")


def is_auto_injected_env(name: str) -> bool:
    return name in _AUTO_ENV_EXACT or bool(_AUTO_ENV_RE.search(name))


def qr_name_for_pod(pod: dict) -> str:
    """Deterministic queued-resource name from the pod UID (RFC-1035 safe).
    The durable pod<->slice binding is this name + the annotation — no local DB
    (state model parity: SURVEY.md §5.4). After a preemption requeue the
    tpu.dev/preemption-count annotation suffixes the name, so the retry can
    never 409-collide with its own dying predecessor (whose delete may still
    be in flight in the real, asynchronous cloud API)."""
    from .annotations import Annotations as A
    u = ko.uid(pod).replace("-", "")[:16].lower() or "nouid"
    attempt = ko.annotations(pod).get(A.PREEMPTION_COUNT, "")
    suffix = f"-r{attempt}" if attempt and attempt != "0" else ""
    return f"qr-{u}{suffix}"


def _decode_secret(secret: dict, key: str) -> str:
    data = secret.get("data", {})
    if key in data:
        return base64.b64decode(data[key]).decode()
    return secret.get("stringData", {}).get(key, "")


def _secret_has_key(secret: dict, key: str) -> bool:
    return key in secret.get("data", {}) or key in secret.get("stringData", {})


def extract_env(kube: KubeClient, pod: dict) -> dict[str, str]:
    """Collect env from ALL containers: plain values, secretKeyRef /
    configMapKeyRef, envFrom secretRef / configMapRef, and secret volumes
    flattened to env (parity: runpod_client.go:949-1054 — which covered
    secrets only; configmaps are what the reference controller's configmap
    informer exists for, main.go:180-193), minus auto-injected cluster
    vars."""
    env: dict[str, str] = {}
    ns = ko.namespace(pod)
    secret_cache: dict[str, dict] = {}
    cm_cache: dict[str, dict] = {}

    def fetch_secret(name: str) -> dict:
        if name not in secret_cache:
            secret_cache[name] = kube.get_secret(ns, name)
        return secret_cache[name]

    def fetch_cm(name: str) -> dict:
        if name not in cm_cache:
            cm_cache[name] = kube.get_config_map(ns, name)
        return cm_cache[name]

    for c in ko.containers(pod):
        for ef in c.get("envFrom", []):
            ref = ef.get("secretRef")
            if ref:
                try:
                    secret = fetch_secret(ref["name"])
                except KubeApiError as e:
                    if ref.get("optional") and e.is_not_found:
                        continue
                    raise TranslationError(
                        f"envFrom secret {ref['name']}: {e}") from e
                for key in secret.get("data", {}):
                    env[ef.get("prefix", "") + key] = _decode_secret(secret, key)
            ref = ef.get("configMapRef")
            if ref:
                try:
                    cm = fetch_cm(ref["name"])
                except KubeApiError as e:
                    if ref.get("optional") and e.is_not_found:
                        continue
                    raise TranslationError(
                        f"envFrom configmap {ref['name']}: {e}") from e
                for key, val in cm.get("data", {}).items():
                    env[ef.get("prefix", "") + key] = val
        for e in c.get("env", []):
            name = e.get("name", "")
            if not name or is_auto_injected_env(name):
                continue
            if "value" in e:
                env[name] = e["value"]
                continue
            src = e.get("valueFrom", {})
            if "secretKeyRef" in src:
                ref = src["secretKeyRef"]
                try:
                    secret = fetch_secret(ref["name"])
                except KubeApiError as ex:
                    if ref.get("optional") and ex.is_not_found:
                        continue
                    raise TranslationError(f"secret {ref['name']}: {ex}") from ex
                # missing KEY in an existing secret fails the pod in real
                # K8s (CreateContainerConfigError) unless optional — a
                # typo'd key must not launch a billable slice w/ empty env
                if not _secret_has_key(secret, ref["key"]):
                    if ref.get("optional"):
                        continue
                    raise TranslationError(
                        f"secret {ref['name']} has no key {ref['key']!r}")
                env[name] = _decode_secret(secret, ref["key"])
            elif "configMapKeyRef" in src:
                ref = src["configMapKeyRef"]
                try:
                    cm = fetch_cm(ref["name"])
                except KubeApiError as ex:
                    if ref.get("optional") and ex.is_not_found:
                        continue
                    raise TranslationError(
                        f"configmap {ref['name']}: {ex}") from ex
                if ref["key"] not in cm.get("data", {}):
                    if ref.get("optional"):
                        continue
                    raise TranslationError(
                        f"configmap {ref['name']} has no key {ref['key']!r}")
                env[name] = cm["data"][ref["key"]]
            elif "fieldRef" in src:
                fp = src["fieldRef"].get("fieldPath", "")
                if fp == "metadata.name":
                    env[name] = ko.name(pod)
                elif fp == "metadata.namespace":
                    env[name] = ns
    # secret volumes -> env (runpod_client.go:949-979 flattening)
    for vol in pod.get("spec", {}).get("volumes", []):
        sec = vol.get("secret")
        if not sec:
            continue
        try:
            secret = fetch_secret(sec["secretName"])
        except KubeApiError as e:
            if sec.get("optional") and e.is_not_found:
                continue
            raise TranslationError(f"volume secret {sec['secretName']}: {e}") from e
        for key in secret.get("data", {}):
            env_name = re.sub(r"[^A-Za-z0-9_]", "_", key).upper()
            env.setdefault(env_name, _decode_secret(secret, key))
    return env


def extract_ports(pod: dict, resolver: AnnotationResolver) -> list[str]:
    """containerPorts across all containers as "port/proto", with the
    tpu.dev/ports annotation as a manual override
    (parity: runpod_client.go:1195-1246 + :1312-1327)."""
    override = resolver.get(A.PORTS)
    if override:
        out = []
        for part in override.split(","):
            part = part.strip()
            if not part:
                continue
            out.append(part if "/" in part else f"{part}/tcp")
        return out
    ports = []
    for c in ko.containers(pod):
        for p in c.get("ports", []):
            proto = p.get("protocol", "TCP").lower()
            ports.append(f"{p['containerPort']}/{proto}")
    return ports


def select_slice(pod: dict, resolver: AnnotationResolver, cfg: Config) -> AcceleratorType:
    """Pick the slice shape: exact annotation, else catalog search by
    (chips requested, generation, topology, HBM floor, cost ceiling).
    Replaces price-sorted GPU selection (runpod_client.go:431-520)."""
    exact = resolver.get(A.ACCELERATOR_TYPE)
    if exact:
        acc = lookup_accelerator(exact)
        if acc is None:
            raise TranslationError(f"unknown accelerator type {exact!r}")
        return acc
    chips = ko.tpu_chips_requested(pod)
    if chips == 0:
        raise TranslationError(
            "pod requests no google.com/tpu chips and sets no "
            f"{A.ACCELERATOR_TYPE} annotation")
    # fleet-scheduler placement (ISSUE 19): a tpu.dev/pool annotation pins
    # the slice to the POOL's generation — the scheduler already paid for
    # that hardware's goodput-per-dollar, so gang launch must not drift to
    # default_generation (an explicit generation annotation, stamped by
    # the same placement, agrees; a conflicting hand-set one loses).
    generation = resolver.get(A.GENERATION) or cfg.default_generation
    pool_name = resolver.get(A.POOL)
    if pool_name and cfg.fleet_pools:
        from ..fleet.scheduler import parse_pools
        for pool in parse_pools(cfg.fleet_pools):
            if pool.name == pool_name:
                generation = pool.generation
                break
        else:
            raise TranslationError(
                f"pod pinned to unknown pool {pool_name!r} "
                f"(fleet_pools={cfg.fleet_pools!r})")
    topology = resolver.get(A.TOPOLOGY) or None
    min_hbm = resolver.get_int(A.MIN_HBM_GIB, 0) or None
    # the pod annotation may only LOWER the operator's ceiling, never raise it
    max_cost = resolver.get_float(A.MAX_COST_PER_HR, 0.0) or None
    if cfg.max_cost_per_hr:
        max_cost = min(max_cost, cfg.max_cost_per_hr) if max_cost else cfg.max_cost_per_hr
    candidates = select_accelerator(chips=chips, generation=generation,
                                    topology=topology, min_hbm_gib=min_hbm,
                                    max_cost_per_hr=max_cost)
    if not candidates:
        raise TranslationError(
            f"no {generation} slice with {chips} chips"
            + (f" topology {topology}" if topology else "")
            + (f" under ${max_cost}/hr" if max_cost else ""))
    return candidates[0]


def resolve_zone(resolver: AnnotationResolver, cfg: Config) -> str:
    """Zone selection with the allowed-zones compliance filter
    (parity: datacenter filter, runpod_client.go:1137-1178)."""
    requested = [z.strip() for z in resolver.get(A.ZONES).split(",") if z.strip()]
    allowed = cfg.zones or None
    if requested:
        usable = [z for z in requested if allowed is None or z in allowed]
        if not usable:
            raise TranslationError(
                f"requested zones {requested} all outside allowed zones {allowed}")
        return usable[0]
    return cfg.zone


def prepare_tpu_parameters(kube: KubeClient, pod: dict, cfg: Config) -> TpuParameters:
    """The full pod -> deploy-request pipeline
    (parity: PrepareRunPodParameters, runpod_client.go:1250-1377)."""
    cs = ko.containers(pod)
    if not cs:
        raise TranslationError("pod has no containers")
    if len(cs) > 1:
        # A TPU slice runs one gang program; sidecars have no analog. Be loud
        # (the reference silently ignored extra containers for image selection).
        log.warning("pod %s has %d containers; the first (%s) is the workload, "
                    "env is merged from all", ko.namespaced_name(pod), len(cs),
                    cs[0].get("name"))
    resolver = AnnotationResolver(kube, pod)

    capacity_type = resolver.get(A.CAPACITY_TYPE, "on-demand").lower()
    if capacity_type not in A.VALID_CAPACITY_TYPES:
        log.warning("pod %s: invalid capacity-type %r — defaulting to on-demand "
                    "(validation parity: runpod_client.go:1115-1134)",
                    ko.namespaced_name(pod), capacity_type)
        capacity_type = "on-demand"
    reservation = resolver.get(A.RESERVATION)
    if capacity_type == "reserved" and not reservation:
        raise TranslationError("capacity-type=reserved requires tpu.dev/reservation")

    acc = select_slice(pod, resolver, cfg)
    zone = resolve_zone(resolver, cfg)
    if cfg.max_cost_per_hr and acc.cost_per_hr > cfg.max_cost_per_hr:
        raise TranslationError(
            f"slice {acc.name} costs ${acc.cost_per_hr}/hr > configured "
            f"ceiling ${cfg.max_cost_per_hr}/hr")

    main = cs[0]
    workload = WorkloadSpec(
        image=main.get("image", ""),
        command=list(main.get("command", [])),
        args=list(main.get("args", [])),
        env=extract_env(kube, pod),
        ports=extract_ports(pod, resolver),
        registry_auth_id=resolver.get(A.REGISTRY_AUTH),
    )
    if not workload.image:
        raise TranslationError("workload container has no image")

    return TpuParameters(
        name=qr_name_for_pod(pod),
        accelerator_type=acc.name,
        runtime_version=(resolver.get(A.RUNTIME_VERSION)
                         or cfg.default_runtime_version or acc.default_runtime),
        zone=zone,
        workload=workload,
        spot=capacity_type == "spot",
        reservation=reservation,
        labels={
            "managed-by": "tpu-virtual-kubelet",
            "pod-uid": ko.uid(pod),
            "pod-namespace": ko.namespace(pod),
            "pod-name": ko.name(pod),
            "node": cfg.node_name,
        },
    )
