"""Steady-state reconcile loops: status diff-and-patch, pending retry, GC ladder.

Rebuild of the reference's loops (kubelet.go:292-317, 734-974, 1188-1377):

- update_all_pod_statuses: the hot loop — poll each slice, gang-launch on
  ACTIVE (the TPU-specific phase 2), translate, patch K8s only on change, with
  the notify-callback fallback wrapped in exception recovery (parity:
  kubelet.go:816-974, panic recovery :938-946).
- process_pending_pods: 30s redeploy of undeployed pods with the 15-min give-up
  -> PodFailed (parity: kubelet.go:734-814). TPU twist: a slice QUEUED in the
  cloud (WAITING_FOR_RESOURCES) is NOT pending-deploy — queueing is normal and
  must not trip the ladder (SURVEY.md §7.4 hard-part #3); it gets its own
  optional max_provisioning_s deadline.
- run_cleanup: tombstone sweep + the stuck-terminating escalation ladder with
  the reference's exact 5/10/15-minute thresholds (kubelet.go:1190-1377).
"""

from __future__ import annotations

import calendar
import logging
import time as _time

from ..cloud.tpu_client import TpuApiError
from ..cloud.types import QueuedResourceState as S
from ..gang.env import compute_worker_env
from ..kube.client import KubeApiError
from ..kube import objects as ko
from ..tracing import Tracer
from .annotations import Annotations as A, AnnotationResolver
from .status import gang_ready, status_fingerprint, translate_status
from .translate import prepare_tpu_parameters, TranslationError

log = logging.getLogger(__name__)


class ReconcileMixin:
    # -- the hot loop ----------------------------------------------------------

    def update_all_pod_statuses(self):
        """One reconcile pass (parity: updateAllPodStatuses kubelet.go:816-974).
        Copy-then-act: snapshot under the lock, then talk to the cloud without
        holding it (lock discipline parity: kubelet.go:817-823).

        Non-reentrant: the 30s status loop and the 10s notify ticker both call
        this; a pass already in flight makes the second caller a no-op, so the
        same pod can never be gang-launched from two threads."""
        if not self._reconcile_guard.acquire(blocking=False):
            return
        try:
            self._update_all_pod_statuses_locked()
        finally:
            self._reconcile_guard.release()

    def _update_all_pod_statuses_locked(self):
        with self.lock:
            snapshot = [(k, ko.deep_copy(p), self.instances.get(k))
                        for k, p in self.pods.items()]
        for key, pod, info in snapshot:
            if info is None:
                continue
            if info.pod_status and info.pod_status.get("phase") in ("Succeeded", "Failed"):
                continue  # terminal — skip (kubelet.go:836-838)
            if not info.qr_name:
                continue  # pending deploy — the pending processor owns it (:841-844)
            try:
                self._reconcile_one(key, pod, info)
                self.note_api_result(True)
            except TpuApiError as e:
                # the API blinked: the pod is NOT failed (it keeps its last
                # cached status); a sustained streak degrades the node
                # (TpuApiReachable=False + NoSchedule taint) until a call
                # succeeds again. Deterministic 4xx (quota 429/403...) is a
                # RESPONSE — the API is alive; only network errors (status
                # 0, incl. CircuitOpenError) and 5xx count as unreachability
                # (mirrors the breaker's own success-on-4xx accounting).
                log.warning("reconcile %s: cloud API error (pod keeps cached "
                            "status): %s", key, e)
                self.note_api_result(0 < e.status < 500)
            except Exception as e:  # noqa: BLE001 — one bad pod must not stop the sweep
                log.exception("reconcile %s failed: %s", key, e)

    def _reconcile_one(self, key: str, pod: dict, info):
        if not info.trace_id or not info.trace_root:
            # recovered/adopted pods may arrive without (full) trace ids —
            # prefer the pod's annotation, mint otherwise; the root is
            # trace_id[:16] (deterministic), so a restart that restored only
            # the annotated trace_id re-parents the remaining lifecycle
            # spans under the SAME pre-restart root
            annotated = ko.annotations(pod).get(A.TRACE_ID, "")
            info.trace_id = info.trace_id or annotated or Tracer.new_trace_id()
            info.trace_root = info.trace_root or info.trace_id[:16]
            if not annotated:
                # write the durable join key back (adopted orphans and pods
                # whose deploy-time annotate never landed): the NEXT restart
                # must restore this trace_id, not mint a third one
                try:
                    ns, name = key.split("/", 1)
                    updated = self.kube.patch_pod(ns, name, {"metadata": {
                        "annotations": {A.TRACE_ID: info.trace_id}}})
                    with self.lock:
                        if key in self.pods:
                            self.pods[key] = updated
                except KubeApiError as e:
                    log.debug("trace-id annotate of %s failed (will retry "
                              "next sweep): %s", key, e)
        detailed = self.tpu.get_detailed_status(info.qr_name, zone=info.zone)
        state = detailed.resource.state

        if state is S.NOT_FOUND:
            self.handle_missing_instance(pod)  # kubelet.go:861-863
            return

        now = self.clock()
        if state is S.ACTIVE and info.active_at is None:
            info.active_at = now
            self.metrics.observe("tpu_kubelet_schedule_to_active_seconds",
                                 now - info.created_at)
            # cloud-side provisioning wait: queued-resource accepted ->
            # slice ACTIVE (the phase Gavel-style schedulers attribute
            # placement cost to). Starts at the CURRENT attempt's deploy,
            # not created_at: after a preemption requeue the span must time
            # this slice's wait, not the pod's whole prior life.
            self.tracer.record("pod.provisioning",
                               info.deployed_at or info.created_at, now,
                               trace_id=info.trace_id,
                               parent_id=info.trace_root,
                               attrs={"pod": key, "slice": info.qr_name,
                                      "accelerator": info.accelerator_type,
                                      "attempt": info.preemption_count})
        if not info.workload_launched and detailed.runtime:
            # a previous launch succeeded server-side but we never saw the
            # response (lost HTTP reply / restart) — adopt it, don't relaunch
            with self.lock:
                info.workload_launched = True
        # TPU phase 2: slice is up, fan the workload out to every worker
        if state is S.ACTIVE and not info.workload_launched:
            self._gang_launch(key, pod, info, detailed)
            detailed = self.tpu.get_detailed_status(info.qr_name, zone=info.zone)
            # re-read the state from the refetch: a preemption landing in the
            # launch->refetch window must hit the requeue path below, not
            # slip past a stale ACTIVE into translate_status as PodFailed
            # (found by the chaos soak: a storm preempting mid-launch
            # permanently failed the pod instead of requeueing it)
            state = detailed.resource.state
            if state is S.NOT_FOUND:
                self.handle_missing_instance(pod)
                return

        # preemption requeue: a SUSPENDED slice can be resubmitted instead of
        # failing the pod, up to cfg.preemption_requeue_limit times
        if state in (S.SUSPENDING, S.SUSPENDED) \
                and info.preemption_count < self.cfg.preemption_requeue_limit:
            self._requeue_preempted(key, pod, info)
            return

        # provisioning-queue deadline (0 = queue forever; see module docstring)
        if (state.is_provisioning and self.cfg.max_provisioning_s
                and now - info.created_at > self.cfg.max_provisioning_s):
            self._fail_pod(pod, "ProvisioningTimeout",
                           f"slice {info.qr_name} not ACTIVE after "
                           f"{self.cfg.max_provisioning_s:.0f}s")
            self._release_slice(key, info)
            return

        # elastic gang resizing (ISSUE 6): partial-gang loss on an ACTIVE
        # slice is NOT whole-slice preemption — an elastic pod shrinks to
        # the survivors (and grows back when capacity returns) instead of
        # requeueing; a checkpointing non-elastic pod requeues instead of
        # hard-failing; everything else keeps the GangBroken contract.
        if state is S.ACTIVE and info.workload_launched and detailed.runtime:
            handled = self._elastic_reconcile(key, pod, info, detailed, now)
            if handled == self.REQUEUED:
                return
            if handled is not None:
                detailed = handled

        # training telemetry (ISSUE 5): scrape worker-0's TPU_TELEMETRY line
        # for running training workloads — annotations, per-pod gauges, and
        # the stall watchdog (TrainingStalled). Best-effort: a scrape
        # failure must never fail the reconcile pass.
        if state is S.ACTIVE and info.workload_launched:
            try:
                self._scrape_training(key, pod, info, detailed, now)
            except Exception as e:  # noqa: BLE001 — observability only
                log.debug("training scrape of %s failed: %s", key, e)

        status = translate_status(pod, detailed,
                                  workload_launched=info.workload_launched)
        fp = status_fingerprint(status)
        with self.lock:
            info.status = state
            if fp == info.fingerprint:
                return  # no change — don't patch (kubelet.go:870-872)
            info.fingerprint = fp
            info.pod_status = status
            is_ready = (status.get("phase") == "Running"
                        and any(c.get("type") == "Ready" and c.get("status") == "True"
                                for c in status.get("conditions", [])))
            ready_now = is_ready and not info.ready
            first_ready = ready_now and info.ready_at is None
            info.ready = is_ready
            if first_ready:
                info.ready_at = now
                self.metrics.observe("tpu_kubelet_schedule_to_ready_seconds",
                                     now - info.created_at)
                log.info("pod %s gang is RUNNING %.1fs after schedule "
                         "(north-star latency)", key, now - info.created_at)
        if ready_now:
            # readiness wait (launch -> all workers Running), recorded per
            # attempt (a preemption requeue re-enters ready)
            start_ready = info.launched_at or info.active_at or info.created_at
            self.tracer.record("pod.ready_wait", start_ready, now,
                               trace_id=info.trace_id,
                               parent_id=info.trace_root,
                               attrs={"pod": key, "slice": info.qr_name,
                                      "attempt": info.preemption_count})
            if first_ready:
                # the ROOT span the phase spans parent under — ONCE, like
                # the north-star metric (a requeue re-ready must not emit a
                # duplicate span_id into the ring/export); recorded last so
                # exports stream children-first but the tree is complete the
                # moment the pod serves traffic
                self.tracer.record("pod.lifecycle", info.created_at, now,
                                   trace_id=info.trace_id,
                                   span_id=info.trace_root,
                                   attrs={"pod": key, "slice": info.qr_name,
                                          "accelerator":
                                              info.accelerator_type,
                                          "schedule_to_ready_s":
                                              now - info.created_at})
            self.emit_event(pod, "GangRunning",
                            f"all workers of {info.qr_name} running "
                            f"{now - info.created_at:.1f}s after schedule")
            if info.preemption_count > 0 and not info.recovery_event_emitted:
                self._emit_preemption_recovery(key, pod, info, detailed, now)
        self._push_status(key, pod, status)
        if status.get("phase") in ("Succeeded", "Failed"):
            # Unlike a RunPod EXITED instance (stopped, not billing), an ACTIVE
            # TPU slice bills until deleted — release it as soon as the pod is
            # terminal. The binding annotation stays for post-mortem.
            self._release_slice(key, info)

    # workloads log this on a successful orbax restore (train.py restore());
    # the recovery event parses the step out of worker-0's logs, best-effort
    _RESUME_STEP_RE = "resumed from checkpoint step (\\d+)"

    def _emit_preemption_recovery(self, key: str, pod: dict, info, detailed,
                                  now: float):
        """A requeued pod came back Ready: close the preemption loop loudly
        (ISSUE 3 part 3) — RecoveredFromPreemption event + span, with the
        checkpoint step the workload actually resumed from when worker-0's
        logs show one (train_main logs it; adopted/serving workloads won't)."""
        resumed_step = None
        if self.gang is not None:
            m = self.gang.find_in_logs(detailed.resource, self._RESUME_STEP_RE)
            if m:
                resumed_step = int(m.group(1))
        with self.lock:
            info.recovery_event_emitted = True
        attrs = {"pod": key, "slice": info.qr_name,
                 "attempt": info.preemption_count}
        if resumed_step is not None:
            attrs["resumed_step"] = resumed_step
        self.tracer.record("pod.preemption_recovery",
                           info.launched_at or info.active_at or now, now,
                           trace_id=info.trace_id, parent_id=info.trace_root,
                           attrs=attrs)
        self.metrics.incr("tpu_kubelet_preemption_recoveries")
        step_note = (f", resumed from checkpoint step {resumed_step}"
                     if resumed_step is not None else "")
        self.emit_event(pod, "RecoveredFromPreemption",
                        f"gang running again on {info.qr_name} after "
                        f"{info.preemption_count} preemption(s){step_note}")
        log.info("pod %s recovered from preemption on %s%s",
                 key, info.qr_name, step_note)
        # durable once-per-attempt marker: a kubelet restart reads this to
        # know THIS attempt already announced (best-effort; a lost patch
        # means at worst one duplicate event after a restart)
        try:
            ns, name = key.split("/", 1)
            updated = self.kube.patch_pod(ns, name, {"metadata": {
                "annotations": {A.RECOVERED_ATTEMPT:
                                str(info.preemption_count)}}})
            with self.lock:
                if key in self.pods:
                    self.pods[key] = updated
        except KubeApiError as e:
            log.debug("recovered-attempt annotate of %s failed: %s", key, e)

    def _tombstone_slice(self, tomb_key: str, qr_name: str, zone: str):
        """Remember a slice whose delete failed so the GC sweep keeps
        re-terminating until it is confirmed gone — failed deletes must
        never leak billable VMs. ``tomb_key`` is namespaced past the pod
        key so it can't collide with delete_pod's own tombstone."""
        from .provider import DeletedPodInfo
        with self.lock:
            self.deleted.setdefault(tomb_key, DeletedPodInfo(
                qr_name=qr_name, zone=zone, deleted_at=self.clock()))

    def _release_slice(self, key: str, info):
        log.info("pod %s is terminal — deleting slice %s to stop billing",
                 key, info.qr_name)
        self._clear_training_gauges(key)
        try:
            self.tpu.delete_queued_resource(info.qr_name, zone=info.zone)
            self.metrics.incr("tpu_kubelet_slices_released")
        except TpuApiError as e:
            log.warning("release of %s failed — tombstoning for the sweep: %s",
                        info.qr_name, e)
            self._tombstone_slice(key + "/released", info.qr_name, info.zone)

    def _requeue_preempted(self, key: str, pod: dict, info):
        """Resubmit a preempted slice (net-new elasticity; SURVEY.md §5.3 notes
        preemption is the common case on TPU). Deletes the dead slice, strips the
        binding, and hands the pod back to the pending processor."""
        info.preemption_count += 1
        log.warning("slice %s of %s preempted — requeueing (attempt %d/%d)",
                    info.qr_name, key, info.preemption_count,
                    self.cfg.preemption_requeue_limit)
        self.emit_event(pod, "Preempted",
                        f"slice {info.qr_name} preempted — requeueing "
                        f"(attempt {info.preemption_count}/"
                        f"{self.cfg.preemption_requeue_limit})",
                        event_type="Warning")
        try:
            self.tpu.delete_queued_resource(info.qr_name, zone=info.zone)
        except TpuApiError as e:
            # a preempted slice whose delete raced a blackout must not leak
            log.warning("delete of preempted %s failed — tombstoning for the "
                        "sweep: %s", info.qr_name, e)
            self._tombstone_slice(f"{key}/preempted-r{info.preemption_count}",
                                  info.qr_name, info.zone)
        try:
            self.kube.patch_pod(pod["metadata"].get("namespace", "default"),
                                pod["metadata"]["name"], {"metadata": {"annotations": {
                                    A.QUEUED_RESOURCE: None,
                                    A.PREEMPTION_COUNT: str(info.preemption_count),
                                    # the replacement slice starts at full
                                    # width: any elastic exclusion dies with
                                    # the old slice (resize-count history
                                    # stays — it never counts against the
                                    # requeue budget)
                                    A.LOST_WORKERS: None,
                                    A.GANG_WIDTH: None,
                                    A.RESIZE_STEP: None}}})
        except KubeApiError as e:
            log.warning("preemption-count annotate of %s failed: %s", key, e)
        # the dead attempt's per-pod gauges go with it — BEFORE the reset
        # below wipes train_last_step (and with it the memory that a
        # stalled=1 series was ever exported)
        self._clear_training_gauges(key)
        with self.lock:
            # keep the cached pod in sync even if the API patch failed: the
            # preemption count feeds qr_name_for_pod, which must never reuse
            # the dying slice's name on the redeploy
            cached = self.pods.get(key)
            if cached is not None:
                anns = cached.setdefault("metadata", {}).setdefault("annotations", {})
                anns.pop(A.QUEUED_RESOURCE, None)
                anns.pop(A.LOST_WORKERS, None)
                anns.pop(A.GANG_WIDTH, None)
                anns.pop(A.RESIZE_STEP, None)
                anns[A.PREEMPTION_COUNT] = str(info.preemption_count)
            info.qr_name = ""
            info.workload_launched = False
            info.ready = False
            info.fingerprint = ()
            info.active_at = None
            # elastic state dies with the slice: the replacement gang is
            # launched at full width
            info.lost_workers = ()
            info.resized_at = None
            info.resize_step = None
            info.deployed_at = None  # next attempt's provisioning span must
            # start at ITS deploy, not this dead slice's
            info.pending_since = self.clock()
            info.recovery_event_emitted = False  # the NEXT recovery announces
            # the relaunch starts a fresh telemetry stream: a stale stall
            # clock must not flag the new attempt before its first scrape
            info.train_last_step = None
            info.train_step_at = None
            info.train_stalled = False
            info.train_annotated = ()
            info.train_first_probe_at = None
            info.train_probe_at = None
        self.metrics.incr("tpu_kubelet_preemption_requeues")

    def _gang_launch(self, key: str, pod: dict, info, detailed):
        """All-or-nothing workload launch with per-worker env (net-new;
        SURVEY.md §2.4 multi-host row)."""
        qr = detailed.resource
        resolver = AnnotationResolver(self.kube, pod)
        num_slices = max(1, resolver.get_int(A.NUM_SLICES, 1))
        slice_id = resolver.get_int(A.SLICE_ID, 0)
        mega = resolver.get(A.MEGASCALE_COORDINATOR) or None
        worker_env = compute_worker_env(
            qr, num_slices=num_slices, slice_id=slice_id,
            megascale_coordinator=mega,
            telemetry_port=self.cfg.telemetry_port,
            straggler_factor=self.cfg.straggler_factor,
            stall_timeout_s=self.cfg.stall_timeout_s)
        try:
            params = prepare_tpu_parameters(self.kube, pod, self.cfg)
        except TranslationError as e:
            log.error("gang launch of %s: translation failed post-deploy: %s", key, e)
            return
        # checkpoint-aware preemption recovery (ISSUE 3): every launch knows
        # its attempt number; relaunches after a preemption also carry the
        # checkpoint dir so training resumes from the latest orbax step
        # instead of step 0 (workloads/train_main.py reads both)
        params.workload.env["TPU_RESTART_ATTEMPT"] = str(info.preemption_count)
        ckpt_dir = (resolver.get(A.CHECKPOINT_DIR)
                    or params.workload.env.get("TPU_CHECKPOINT_DIR", ""))
        if ckpt_dir:
            params.workload.env["TPU_CHECKPOINT_DIR"] = ckpt_dir
        launch_started = self.clock()
        try:
            self.tpu.start_workload(info.qr_name, params.workload,
                                    worker_env=worker_env, zone=info.zone)
        except TpuApiError as e:
            log.warning("gang launch of %s on %s failed (will retry): %s",
                        key, info.qr_name, e)
            self.emit_event(pod, "GangLaunchFailed",
                            f"workload launch on {info.qr_name} failed "
                            f"(will retry): {e}", event_type="Warning")
            return
        with self.lock:
            info.workload_launched = True
            info.launched_at = self.clock()
        self.tracer.record("pod.gang_launch", launch_started,
                           info.launched_at, trace_id=info.trace_id,
                           parent_id=info.trace_root,
                           attrs={"pod": key, "slice": info.qr_name,
                                  "workers": len(qr.workers)})
        self.metrics.incr("tpu_kubelet_gang_launches")
        log.info("gang-launched %s on %s (%d workers, %d slice(s))",
                 key, info.qr_name, len(qr.workers), num_slices)
        self.emit_event(pod, "GangLaunched",
                        f"workload launched on all {len(qr.workers)} workers "
                        f"of {info.qr_name}")

    def _push_status(self, key: str, pod: dict, status: dict):
        """Patch pods/status; on failure fall back to the notify callback with
        exception recovery (parity: kubelet.go:915-957)."""
        ns, name = key.split("/", 1)
        try:
            self.kube.patch_pod_status(ns, name, {"status": status})
            return
        except KubeApiError as e:
            log.warning("status patch of %s failed: %s — trying notify fallback", key, e)
        cb = self._notify_cb
        if cb is None:
            return
        updated = ko.deep_copy(pod)
        updated["status"] = status
        try:
            cb(updated)
        except Exception as e:  # noqa: BLE001 — recovery parity kubelet.go:938-946
            log.exception("notify callback panicked: %s", e)

    def _fail_pod(self, pod: dict, reason: str, message: str):
        key = self.key_of(pod)
        status = {
            "phase": "Failed", "reason": reason, "message": message,
            "conditions": [{"type": "Ready", "status": "False", "reason": reason}],
        }
        with self.lock:
            info = self.instances.get(key)
            if info:
                info.pod_status = status
                info.fingerprint = status_fingerprint(status)
        self._push_status(key, pod, status)
        log.warning("pod %s failed: %s: %s", key, reason, message)
        self.emit_event(pod, reason, message, event_type="Warning")

    # -- pending deploys -------------------------------------------------------

    def has_pending_reference(self, kind: str, ns: str, name: str) -> bool:
        """Does any PENDING (undeployed) pod consume this secret/configmap?
        The ref-resource watcher uses this to turn an object change into an
        immediate deploy retry instead of waiting out the 30s ticker."""
        with self.lock:
            return any(
                ko.namespace(p) == ns
                and ko.pod_references_object(p, kind, name)
                for k, p in self.pods.items()
                if (i := self.instances.get(k)) is not None
                and not i.qr_name and i.pending_since is not None)

    def process_pending_pods(self):
        """Retry undeployed pods; give up after max_pending_s
        (parity: startPendingPodProcessor kubelet.go:734-814)."""
        with self.lock:
            pending = [(k, ko.deep_copy(p)) for k, p in self.pods.items()
                       if (i := self.instances.get(k)) is not None
                       and not i.qr_name and i.pending_since is not None]
        now = self.clock()
        for key, pod in pending:
            with self.lock:
                info = self.instances.get(key)
                if info is None or info.qr_name:
                    continue
                waited = now - (info.pending_since or now)
                last_err = info.last_deploy_error
            if waited > self.cfg.max_pending_s:
                self._fail_pod(pod, "DeploymentFailed",
                               f"could not deploy for {waited:.0f}s"
                               + (f"; last error: {last_err}" if last_err else ""))
                with self.lock:
                    if key in self.instances:
                        self.instances[key].pending_since = None
                continue
            log.info("retrying deploy of pending pod %s (%.0fs elapsed)", key, waited)
            self.deploy_pod(pod)

    # -- garbage collection ----------------------------------------------------

    def run_cleanup(self):
        self.cleanup_deleted_pods()
        self.cleanup_stuck_terminating_pods()
        self.cleanup_orphaned_slices()

    def cleanup_deleted_pods(self):
        """Tombstone sweep: keep terminating the slice until it is actually gone,
        then drop the tombstone (parity: cleanupDeletedPods kubelet.go:1190-1227)."""
        with self.lock:
            items = list(self.deleted.items())
        for key, tomb in items:
            try:
                self.tpu.get_queued_resource(tomb.qr_name, zone=tomb.zone)
            except TpuApiError as e:
                if e.status == 404:
                    with self.lock:
                        self.deleted.pop(key, None)
                    continue
                log.warning("cleanup: status of %s unknown: %s", tomb.qr_name, e)
                continue
            now = self.clock()
            if now - tomb.last_terminate_at > 60:
                log.info("cleanup: slice %s of deleted pod %s still exists — "
                         "re-terminating", tomb.qr_name, key)
                try:
                    self.tpu.delete_queued_resource(tomb.qr_name, zone=tomb.zone)
                    tomb.last_terminate_at = now
                except TpuApiError as e:
                    log.warning("cleanup re-terminate %s failed: %s", tomb.qr_name, e)

    def cleanup_stuck_terminating_pods(self):
        """The escalation ladder for pods stuck Terminating, with the reference's
        thresholds (parity: cleanupStuckTerminatingPods kubelet.go:1231-1377):
          - no slice id                          -> force delete now   (:1253-1271)
          - slice status unreachable > 10 min    -> force delete       (:1284-1301)
          - slice still up, > 5 min              -> re-terminate       (:1332-1347)
          - > 15 min regardless                  -> force delete       (:1350-1366)
        """
        try:
            pods = self.kube.list_pods(
                field_selector=f"spec.nodeName={self.cfg.node_name}")
        except KubeApiError as e:
            log.warning("stuck-terminating sweep: list failed: %s", e)
            return
        now = self.clock()
        # prune unreachable-tracking for pods that left by ANY path (external
        # force-delete included) — a later same-named pod must not inherit a
        # stale first-unreachable timestamp and lose its grace period
        with self.lock:
            live = {ko.namespaced_name(p) for p in pods}
            for k in list(self._stuck_unreachable):
                if k not in live:
                    self._stuck_unreachable.pop(k, None)
        for pod in pods:
            ts = ko.deletion_timestamp(pod)
            if not ts:
                continue
            key = ko.namespaced_name(pod)
            try:
                deleting_for = now - calendar.timegm(
                    _time.strptime(ts, "%Y-%m-%dT%H:%M:%SZ"))
            except ValueError:
                deleting_for = 0.0
            qr_name = ko.annotations(pod).get(A.QUEUED_RESOURCE, "")
            zone = ko.annotations(pod).get(A.ZONE, "") or self.cfg.zone
            if not qr_name:
                log.info("stuck pod %s has no slice — force deleting", key)
                self.force_delete_pod(pod)
                continue
            try:
                self.tpu.get_queued_resource(qr_name, zone=zone)
                reachable = True
            except TpuApiError as e:
                reachable = e.status == 404
                if e.status == 404:
                    log.info("stuck pod %s: slice already gone — force deleting", key)
                    self.force_delete_pod(pod)
                    continue
            if not reachable:
                # dedicated per-pod-key tracking: tombstones in self.deleted
                # are keyed differently (delete_pod uses the pod key but
                # _release_slice appends "/released"), so piggybacking on
                # them silently missed this path (VERDICT r1 weak #8)
                with self.lock:
                    since = self._stuck_unreachable.setdefault(key, now)
                unreachable_for = now - since
                if unreachable_for > self.cfg.stuck_unreachable_force_s \
                        or deleting_for > self.cfg.stuck_unreachable_force_s:
                    log.warning("stuck pod %s: slice unreachable >%.0fs — force deleting",
                                key, self.cfg.stuck_unreachable_force_s)
                    self.force_delete_pod(pod)  # pops the unreachable entry
                continue
            with self.lock:
                self._stuck_unreachable.pop(key, None)  # reachable again
            if deleting_for > self.cfg.stuck_force_delete_s:
                log.warning("stuck pod %s terminating for %.0fs — force deleting "
                            "and abandoning slice %s to the tombstone sweep",
                            key, deleting_for, qr_name)
                self.force_delete_pod(pod)
            elif deleting_for > self.cfg.stuck_reterminate_s:
                log.info("stuck pod %s terminating for %.0fs — re-terminating %s",
                         key, deleting_for, qr_name)
                try:
                    self.tpu.delete_queued_resource(qr_name, zone=zone)
                except TpuApiError as e:
                    log.warning("re-terminate %s failed: %s", qr_name, e)

    def cleanup_orphaned_slices(self):
        """Slices labeled as ours whose pod no longer exists in K8s -> delete.
        Stronger than the reference (which only sweeps its in-memory deleted
        map): this catches slices leaked across kubelet restarts."""
        try:
            slices = self.tpu.list_queued_resources()
        except TpuApiError as e:
            log.warning("orphan sweep: list failed: %s", e)
            return
        with self.lock:
            known = {i.qr_name for i in self.instances.values() if i.qr_name}
            tombs = {t.qr_name for t in self.deleted.values()}
        for qr in slices:
            if qr.labels.get("managed-by") != "tpu-virtual-kubelet":
                continue
            if qr.labels.get("node") != self.cfg.node_name:
                continue
            if qr.name in known or qr.name in tombs:
                continue
            ns = qr.labels.get("pod-namespace", "")
            name = qr.labels.get("pod-name", "")
            try:
                self.kube.get_pod(ns, name)
                continue  # pod exists; recovery will adopt it
            except KubeApiError as e:
                if not e.is_not_found:
                    continue
            log.warning("orphan sweep: slice %s has no pod %s/%s — deleting",
                        qr.name, ns, name)
            try:
                self.tpu.delete_queued_resource(qr.name, zone=qr.zone or None)
            except TpuApiError as e:
                log.warning("orphan delete %s failed: %s", qr.name, e)
