"""Elastic gang resizing: shrink to the survivors on host loss, grow back.

The control-plane half of ISSUE 6. Preemption recovery (recovery.py +
reconcile.py's requeue) restarts the SAME-SIZE gang from its checkpoint —
correct for whole-slice preemption, wasteful for a single lost host: the
surviving N-1 hosts idle until the cloud grants a full replacement slice,
and the GoodputLedger charges all of it to ``restart_lost``. This mixin
distinguishes the two:

- **whole-slice preemption** (SUSPENDED/SUSPENDING): unchanged — requeue,
  consuming ``preemption_requeue_limit`` budget;
- **partial-gang loss** (slice ACTIVE, some workers unhealthy): for a pod
  annotated ``tpu.dev/elastic=true``, relaunch the workload on the
  SURVIVING workers only — gang/env.py renumbers JAX process ids densely,
  the lowest survivor becomes coordinator, and the injected
  ``TPU_ELASTIC_RESIZE`` / ``TPU_GANG_FULL_HOSTS`` ride the same
  env-injection path as ``TPU_RESTART_ATTEMPT`` so train_main reshards
  from the latest orbax checkpoint at the surviving DP width
  (workloads/train.py ``Trainer.resize`` is the in-process analog). A
  resize never consumes the preemption-requeue budget (resize-count is
  tracked separately, and pinned by a regression test).

While shrunk, the kubelet keeps a replacement request open (the
``ReplacementRequested`` event; on Cloud TPU a queued-resource's worker is
re-delivered by the infrastructure — the fake cloud models it as the
host_loss fault window closing) and **grows back** once every worker is
healthy again — preferring the next checkpoint boundary (a `checkpoint
saved at step N` line in worker-0 logs newer than the shrink) so the
re-restore loses nothing, with ``elastic_grow_grace_s`` as the fallback
when the workload never checkpoints. Both directions emit a ``GangResized``
event and a ``pod.gang_resize`` span joined to the pod's lifecycle trace.

Pods below ``tpu.dev/elastic-min-hosts`` survivors (or non-elastic pods
with a checkpoint dir and requeue budget) fall back to the requeue path;
pods with neither keep the original gang-fail contract (GangBroken ->
Failed, the owning Job recreates).
"""

from __future__ import annotations

import dataclasses
import logging

from ..cloud.tpu_client import TpuApiError
from ..cloud.types import DetailedStatus
from ..gang.env import compute_worker_env
from ..kube.client import KubeApiError
from ..kube import objects as ko
from .annotations import Annotations as A, AnnotationResolver
from .translate import prepare_tpu_parameters, TranslationError

log = logging.getLogger(__name__)

# train.py logs "saved" on blocking saves and "staged" on async ones (the
# run() loop's default); the grow path greps either to grow at a checkpoint
# boundary. A staged write may be seconds from durable, but the grown gang's
# orbax restore only ever reads COMMITTED steps, so the worst case is
# resuming one checkpoint earlier — still bounded, unlike growing blind.
_CHECKPOINT_SAVED_RE = r"checkpoint (?:saved|staged) at step (\d+)"


def _lost_worker_ids(detailed: DetailedStatus) -> set[int]:
    return {w.worker_id for w in detailed.runtime if not w.healthy}


class ElasticGangMixin:
    def _describe_elastic_metrics(self):
        m = self.metrics
        m.describe("tpu_kubelet_gang_resizes",
                   "elastic gang resizes performed (kind label: shrink/grow)")
        m.describe("tpu_kubelet_gang_resize_failures",
                   "resize relaunches that failed (retried next sweep)")
        m.describe("tpu_kubelet_host_loss_requeues",
                   "partial-gang losses handled by a full requeue "
                   "(non-elastic pod with checkpoint dir + budget)")

    # -- policy ----------------------------------------------------------------

    def _elastic_enabled(self, pod: dict) -> bool:
        if not getattr(self.cfg, "elastic_resize", True):
            return False
        return ko.annotations(pod).get(A.ELASTIC, "").lower() in ("1", "true",
                                                                  "yes")

    def _elastic_min_hosts(self, pod: dict) -> int:
        try:
            return max(1, int(ko.annotations(pod).get(
                A.ELASTIC_MIN_HOSTS, "1") or 1))
        except ValueError:
            return 1

    def _is_multislice(self, pod: dict) -> bool:
        resolver = AnnotationResolver(self.kube, pod)
        return max(1, resolver.get_int(A.NUM_SLICES, 1)) > 1

    # -- the reconcile hook ----------------------------------------------------

    #: sentinel: the elastic pass requeued the pod; the reconcile pass must
    #: stop (the slice is being deleted; its stale status must not be pushed)
    REQUEUED = "requeued"

    def _elastic_reconcile(self, key: str, pod: dict, info, detailed,
                           now: float):
        """Called by _reconcile_one for an ACTIVE, launched slice whose
        runtime is known. Applies the shrink/grow state machine and returns
        the DetailedStatus the rest of the pass should see — with the
        currently-excluded workers FILTERED OUT, so translate_status judges
        the surviving gang (Running while the survivors run) instead of
        failing the pod for a loss the resize already absorbed. Returns
        ``ElasticGangMixin.REQUEUED`` when it routed the pod to the requeue
        ladder (caller stops the pass), or None when the pod is not elastic
        or nothing needs hiding."""
        if not detailed.runtime:
            return None
        lost = _lost_worker_ids(detailed)
        excluded = set(info.lost_workers)
        total = len(detailed.resource.workers)

        if not self._elastic_enabled(pod):
            if lost and len(lost) < total \
                    and self._host_loss_requeue(key, pod, info, lost):
                return self.REQUEUED
            return None

        min_hosts = self._elastic_min_hosts(pod)
        survivors = sorted(w.worker_id for w in detailed.resource.workers
                           if w.worker_id not in lost)

        resized = False
        if lost - excluded:
            if self._is_multislice(pod):
                # shrinking ONE slice of a multislice gang would renumber
                # only this slice's process space while the sibling slices
                # keep the old JAX_NUM_PROCESSES — the cross-slice
                # rendezvous deadlocks. Until multislice-wide coordination
                # exists, host loss on a multislice pod requeues.
                log.warning("pod %s: host loss on a multislice gang — "
                            "resize is single-slice only, requeueing", key)
                if self._host_loss_requeue(key, pod, info, lost, force=True):
                    return self.REQUEUED
                return None
            if not survivors or len(survivors) < min_hosts:
                # nothing (or too little) left to resize onto: the loss
                # degenerates to the requeue/gang-fail ladder
                log.warning("pod %s: %d/%d workers lost — below elastic "
                            "min_hosts=%d, falling back to requeue",
                            key, len(lost), total, min_hosts)
                if self._host_loss_requeue(key, pod, info, lost,
                                           force=True):
                    return self.REQUEUED
                return None
            self._resize_gang(key, pod, info, detailed, survivors,
                              kind="shrink", lost=sorted(lost), now=now)
            resized = True
        elif excluded and not lost and self._grow_ready(info, detailed, now):
            # every excluded worker is healthy again: capacity returned —
            # grow back at the checkpoint boundary (or after the grace)
            self._resize_gang(key, pod, info, detailed,
                              [w.worker_id
                               for w in detailed.resource.workers],
                              kind="grow", lost=[], now=now)
            resized = True
        # else: steady shrunk state, or a PARTIAL return (some excluded
        # workers healed, others still dead) — keep waiting; growing in two
        # steps would thrash the gang with restarts

        if resized:
            # judge THIS pass on the post-relaunch world (the gang-launch
            # refetch pattern): a stale runtime would show the pre-resize
            # container states
            detailed = self.tpu.get_detailed_status(info.qr_name,
                                                    zone=info.zone)
        with self.lock:
            excluded_now = set(info.lost_workers)
        if not excluded_now:
            if not resized and ko.annotations(pod).get(A.LOST_WORKERS):
                # a grow whose annotation clear failed: retry, else a
                # kubelet restart would re-exclude healthy workers (when
                # resized, _resize_gang just patched this pass)
                self._annotate_resize(key, pod, info, total, total)
            return dataclasses.replace(detailed) if resized else None
        if not resized:
            # steady shrunk state: re-issue the durable-state patch when a
            # prior attempt failed (the "next sweep retries" promise) — a
            # kubelet restart reading a stale empty tpu.dev/lost-workers
            # would otherwise re-shrink an already-shrunk gang
            want = ",".join(str(w) for w in sorted(excluded_now))
            if ko.annotations(pod).get(A.LOST_WORKERS, "") != want:
                self._annotate_resize(key, pod, info,
                                      total - len(excluded_now), total)
        filtered = [w for w in detailed.runtime
                    if w.worker_id not in excluded_now]
        return dataclasses.replace(detailed, runtime=filtered)

    def _grow_ready(self, info, detailed, now: float) -> bool:
        """Grow at a checkpoint boundary: a `checkpoint saved at step N` log
        line on the scrape worker NEWER than the shrink means the restore
        after the grow re-loses nothing. Workloads that never checkpoint
        (or whose logs are unreadable) grow after elastic_grow_grace_s —
        staying shrunk forever is strictly worse."""
        if info.resized_at is None:
            return True
        grace = getattr(self.cfg, "elastic_grow_grace_s", 120.0)
        if self.gang is not None:
            m = self.gang.last_in_logs(detailed.resource, _CHECKPOINT_SAVED_RE,
                                       worker_id=self.scrape_worker_id(info))
            if m is not None and int(m.group(1)) >= (info.resize_step or 0):
                return True
        return now - info.resized_at >= grace

    def scrape_worker_id(self, info) -> int:
        """The worker whose logs carry worker-0 output: the lowest SURVIVING
        id — after an elastic shrink that excluded worker 0, the renumbered
        process 0 (coordinator, telemetry aggregator) lives on the next
        surviving VM."""
        excluded = set(info.lost_workers)
        wid = 0
        while wid in excluded:
            wid += 1
        return wid

    # -- the two transitions ---------------------------------------------------

    def _resize_gang(self, key: str, pod: dict, info, detailed,
                     worker_ids: list[int], *, kind: str, lost: list[int],
                     now: float):
        """Relaunch the workload on ``worker_ids`` (all workers for a grow),
        riding the TPU_RESTART_ATTEMPT/TPU_CHECKPOINT_DIR injection path
        plus the elastic vars, and record the event/span/annotations. A
        failed relaunch leaves the exclusion state UNCHANGED so the next
        sweep retries."""
        qr = detailed.resource
        resolver = AnnotationResolver(self.kube, pod)
        num_slices = max(1, resolver.get_int(A.NUM_SLICES, 1))
        slice_id = resolver.get_int(A.SLICE_ID, 0)
        mega = resolver.get(A.MEGASCALE_COORDINATOR) or None
        subset = worker_ids if kind == "shrink" else None
        worker_env = compute_worker_env(
            qr, num_slices=num_slices, slice_id=slice_id,
            megascale_coordinator=mega,
            telemetry_port=self.cfg.telemetry_port,
            straggler_factor=self.cfg.straggler_factor,
            stall_timeout_s=self.cfg.stall_timeout_s,
            worker_ids=subset)
        try:
            params = prepare_tpu_parameters(self.kube, pod, self.cfg)
        except TranslationError as e:
            log.error("resize of %s: translation failed: %s", key, e)
            return
        next_count = info.resize_count + 1
        env = params.workload.env
        # the SAME attempt number: a resize is not a requeue, and the
        # workload-side ledger uses the (attempt, resize) pair to charge
        # the downtime to `resize` instead of `restart_lost`
        env["TPU_RESTART_ATTEMPT"] = str(info.preemption_count)
        env["TPU_ELASTIC_RESIZE"] = str(next_count)
        env["TPU_GANG_FULL_HOSTS"] = str(len(qr.workers))
        batch_mode = resolver.get(A.ELASTIC_BATCH_MODE)
        if batch_mode:
            env["TPU_ELASTIC_BATCH_MODE"] = batch_mode
        ckpt_dir = (resolver.get(A.CHECKPOINT_DIR)
                    or env.get("TPU_CHECKPOINT_DIR", ""))
        if ckpt_dir:
            env["TPU_CHECKPOINT_DIR"] = ckpt_dir
        started = self.clock()
        try:
            self.tpu.start_workload(info.qr_name, params.workload,
                                    worker_env=worker_env, zone=info.zone,
                                    worker_ids=subset)
        except TpuApiError as e:
            log.warning("elastic %s of %s on %s failed (retrying next "
                        "sweep): %s", kind, key, info.qr_name, e)
            self.metrics.incr("tpu_kubelet_gang_resize_failures")
            self.emit_event(pod, "GangResizeFailed",
                            f"elastic {kind} relaunch on {info.qr_name} "
                            f"failed (will retry): {e}",
                            event_type="Warning")
            return
        width = len(worker_ids)
        total = len(qr.workers)
        with self.lock:
            info.resize_count = next_count
            info.lost_workers = tuple(lost)
            info.resized_at = self.clock()
            info.resize_step = info.train_last_step
            info.ready = False          # the resized gang re-enters ready
            info.fingerprint = ()
            # fresh telemetry stream at the new width: the stall clock must
            # not flag the resized gang off the old attempt's silence
            info.train_step_at = None
            info.train_stalled = False
        self.tracer.record("pod.gang_resize", started, info.resized_at,
                           trace_id=info.trace_id, parent_id=info.trace_root,
                           attrs={"pod": key, "slice": info.qr_name,
                                  "kind": kind, "width": width,
                                  "full_width": total,
                                  "lost_workers": lost,
                                  "resize": next_count})
        self.metrics.incr("tpu_kubelet_gang_resizes", labels={"kind": kind})
        if kind == "shrink":
            msg = (f"host loss on {info.qr_name}: workers {lost} lost — gang "
                   f"resized to {width}/{total} surviving hosts (resize "
                   f"#{next_count}); requeue budget untouched")
        else:
            msg = (f"capacity returned on {info.qr_name}: gang grown back to "
                   f"{width}/{total} hosts from the latest checkpoint "
                   f"(resize #{next_count})")
        log.warning("pod %s: %s", key, msg)
        self.emit_event(pod, "GangResized", msg,
                        event_type="Warning" if kind == "shrink" else "Normal")
        if kind == "shrink":
            # keep the replacement ask visible: on Cloud TPU the queued
            # resource's missing worker is re-delivered by the service; the
            # fake cloud models it as the host_loss window closing
            self.emit_event(pod, "ReplacementRequested",
                            f"waiting for {total - width} replacement "
                            f"host(s) on {info.qr_name}; will grow back at "
                            "the next checkpoint boundary")
        self._annotate_resize(key, pod, info, width, total)

    def _annotate_resize(self, key: str, pod: dict, info, width: int,
                         total: int):
        """Durable mirrors of the resize state (restored by recovery.py so a
        kubelet restart mid-shrink neither forgets the exclusion nor
        re-shrinks an already-shrunk gang)."""
        anns = {A.RESIZE_COUNT: str(info.resize_count),
                A.GANG_WIDTH: f"{width}/{total}",
                A.LOST_WORKERS: ",".join(str(w) for w in info.lost_workers)
                or None,
                A.RESIZE_STEP: str(info.resize_step)
                if info.resize_step is not None and info.lost_workers
                else None}
        try:
            ns, name = key.split("/", 1)
            updated = self.kube.patch_pod(ns, name,
                                          {"metadata": {"annotations": anns}})
            with self.lock:
                if key in self.pods:
                    self.pods[key] = updated
        except KubeApiError as e:
            log.debug("resize annotate of %s failed (next sweep retries): %s",
                      key, e)

    def _host_loss_requeue(self, key: str, pod: dict, info, lost: set[int],
                           force: bool = False) -> bool:
        """Partial-gang loss routed to a full requeue: pods that opted into
        checkpointing (tpu.dev/checkpoint-dir) with requeue budget left get
        the restart-from-checkpoint-of-the-same-size-gang treatment — the
        PR 3 baseline the elastic path is measured against — instead of a
        hard GangBroken failure. Pods with neither keep the original
        gang-fail contract (translate_status Fails them this same pass).
        ``force``: an elastic pod below its min-hosts floor requeues even
        without a checkpoint annotation (it opted into staying alive).
        Returns True when the pod was requeued."""
        anns = ko.annotations(pod)
        if not force and not anns.get(A.CHECKPOINT_DIR):
            return False
        if info.preemption_count >= self.cfg.preemption_requeue_limit:
            return False
        log.warning("pod %s: workers %s lost on %s — requeueing the whole "
                    "slice (restart-from-checkpoint at full width)",
                    key, sorted(lost), info.qr_name)
        self.metrics.incr("tpu_kubelet_host_loss_requeues")
        self._requeue_preempted(key, pod, info)
        return True
