"""tpu.dev/* annotation schema and resolution.

TPU-native successor of the runpod.io/* annotation surface
(runpod_client.go:37-46; SURVEY.md §2.2), with the same pod-over-Job fallback
semantics (annotation on the pod wins; else the owning Job's annotation applies,
runpod_client.go:1102-1112 + getOwnerJob :1057-1099 with owner-UID check).
"""

from __future__ import annotations

import logging
from typing import Optional

from ..kube.client import KubeApiError, KubeClient
from ..kube import objects as ko

log = logging.getLogger(__name__)


class Annotations:
    PREFIX = "tpu.dev/"

    # instance binding + cost (runpod.io/pod-id, runpod.io/cost-per-hr)
    QUEUED_RESOURCE = "tpu.dev/queued-resource-id"
    COST_PER_HR = "tpu.dev/cost-per-hr"
    ZONE = "tpu.dev/zone"  # where the bound slice actually lives

    # slice selection (replaces cloud-type/templateId/required-gpu-memory)
    ACCELERATOR_TYPE = "tpu.dev/accelerator-type"   # exact, e.g. v5litepod-16
    GENERATION = "tpu.dev/generation"               # e.g. v5e

    # fleet scheduler placement (ISSUE 19): which node pool the fleet
    # scheduler reserved for this pod. POOL pins slice selection to the
    # pool's generation at gang launch; POOL_KIND + BEST_EFFORT let a
    # restarted scheduler rebuild its reservation table from live pods
    # (FleetScheduler.adopt) without double-placing or orphaning anything.
    POOL = "tpu.dev/pool"
    POOL_KIND = "tpu.dev/pool-kind"                 # prefill|decode|unified|training
    BEST_EFFORT = "tpu.dev/best-effort"             # "true" => preemptible filler
    TOPOLOGY = "tpu.dev/topology"                   # e.g. 4x4
    RUNTIME_VERSION = "tpu.dev/runtime-version"
    CAPACITY_TYPE = "tpu.dev/capacity-type"         # on-demand | spot | reserved
    RESERVATION = "tpu.dev/reservation"
    MIN_HBM_GIB = "tpu.dev/min-hbm-gib"             # ~ runpod.io/required-gpu-memory
    MAX_COST_PER_HR = "tpu.dev/max-cost-per-hr"
    ZONES = "tpu.dev/zones"                         # ~ runpod.io/datacenter-ids

    # workload
    PORTS = "tpu.dev/ports"                         # ~ runpod.io/ports override
    REGISTRY_AUTH = "tpu.dev/registry-auth-id"      # ~ container-registry-auth-id

    # multislice (net-new)
    NUM_SLICES = "tpu.dev/num-slices"
    SLICE_ID = "tpu.dev/slice-id"
    MEGASCALE_COORDINATOR = "tpu.dev/megascale-coordinator"

    # checkpoint-aware preemption recovery (ISSUE 3): where the workload
    # writes its orbax checkpoints. On a post-preemption relaunch the gang
    # gets TPU_CHECKPOINT_DIR + TPU_RESTART_ATTEMPT injected so training
    # resumes from the latest step instead of step 0 (workloads/train_main.py
    # consumes both).
    CHECKPOINT_DIR = "tpu.dev/checkpoint-dir"

    # elastic gang training (ISSUE 6): opt-in resize-instead-of-restart on
    # partial host loss. ELASTIC="true" makes the kubelet relaunch the gang
    # on the surviving hosts (mesh rebuilt at the surviving DP width, state
    # resharded from the latest checkpoint) instead of requeueing the whole
    # slice; MIN_HOSTS is the floor below which it requeues after all.
    # RESIZE_COUNT / LOST_WORKERS are durable state (mirrors of
    # InstanceInfo, restored on kubelet restart): the cumulative shrink/grow
    # count — deliberately SEPARATE from preemption-count, a resize never
    # consumes the requeue budget — and the currently-excluded worker ids.
    # GANG_WIDTH ("surviving/total") is the operator-visible width.
    ELASTIC = "tpu.dev/elastic"
    ELASTIC_MIN_HOSTS = "tpu.dev/elastic-min-hosts"
    ELASTIC_BATCH_MODE = "tpu.dev/elastic-batch-mode"  # global | per_host
    RESIZE_COUNT = "tpu.dev/resize-count"
    LOST_WORKERS = "tpu.dev/lost-workers"
    GANG_WIDTH = "tpu.dev/gang-width"
    # the scraped training step when the shrink happened: the grow path only
    # treats a `checkpoint saved/staged at step N` log line as a boundary
    # when N is at least this — durable so a kubelet restart can't mistake a
    # PRE-shrink checkpoint line for a fresh boundary
    RESIZE_STEP = "tpu.dev/resize-step"

    # bookkeeping
    EXTERNAL = "tpu.dev/external"                   # adopted orphan (kubelet.go:1580)
    PREEMPTION_COUNT = "tpu.dev/preemption-count"
    # the attempt number whose RecoveredFromPreemption event was emitted —
    # durable so a kubelet restart neither re-announces an already-announced
    # recovery nor swallows one that hadn't been announced yet
    RECOVERED_ATTEMPT = "tpu.dev/recovered-attempt"
    # training telemetry (ISSUE 5): the reconcile loop scrapes worker-0's
    # TPU_TELEMETRY log line for Running training pods and mirrors the
    # progress signals here, so `kubectl get pod -o yaml` (and the fleet
    # tier) can read goodput/MFU/progress without touching the workers
    GOODPUT = "tpu.dev/goodput"
    MFU = "tpu.dev/mfu"
    LAST_STEP = "tpu.dev/last-step"

    # observability: the trace_id shared by this pod's lifecycle spans
    # (create -> deploy -> ACTIVE -> ready). Durable on the pod so a slow
    # serving request on the slice can be joined back to how it was born
    # (clients send it as the traceparent trace id; /debug/traces?trace_id=
    # then shows provisioning AND serving spans in one tree).
    TRACE_ID = "tpu.dev/trace-id"

    VALID_CAPACITY_TYPES = ("on-demand", "spot", "reserved")


def get_owner_job(kube: KubeClient, pod: dict) -> Optional[dict]:
    """The pod's owning Job, verified by owner-reference UID
    (parity: runpod_client.go:1057-1099)."""
    for ref in ko.owner_references(pod):
        if ref.get("kind") == "Job":
            try:
                job = kube.get_job(ko.namespace(pod), ref["name"])
            except KubeApiError as e:
                if e.is_not_found:
                    continue
                raise
            if ref.get("uid") and ko.uid(job) and ref["uid"] != ko.uid(job):
                log.warning("job %s uid mismatch for pod %s — stale owner ref",
                            ref["name"], ko.name(pod))
                continue
            return job
    return None


class AnnotationResolver:
    """Resolves annotations with pod > owning-Job precedence. Fetches the Job at
    most once per pod."""

    def __init__(self, kube: KubeClient, pod: dict):
        self.pod = pod
        self._kube = kube
        self._job: Optional[dict] = None
        self._job_fetched = False

    def _job_annotations(self) -> dict[str, str]:
        if not self._job_fetched:
            self._job_fetched = True
            try:
                self._job = get_owner_job(self._kube, self.pod)
            except KubeApiError as e:
                log.warning("owner-job lookup failed for %s: %s",
                            ko.namespaced_name(self.pod), e)
                self._job = None
        return ko.annotations(self._job) if self._job else {}

    def get(self, key: str, default: str = "") -> str:
        v = ko.annotations(self.pod).get(key)
        if v is not None and v != "":
            return v
        return self._job_annotations().get(key, default)

    def get_float(self, key: str, default: float = 0.0) -> float:
        v = self.get(key)
        if not v:
            return default
        try:
            return float(v)
        except ValueError:
            log.warning("pod %s: annotation %s=%r is not a number — using %s",
                        ko.namespaced_name(self.pod), key, v, default)
            return default

    def get_int(self, key: str, default: int = 0) -> int:
        return int(self.get_float(key, float(default)))
