"""The virtual Node object: capacity, labels, taint, conditions.

Parity with GetNodeStatus (kubelet.go:1098-1186), retargeted: capacity
advertises ``google.com/tpu`` (not nvidia.com/gpu:4), plus topology labels so
mesh-aware workloads can size themselves (SURVEY.md §2.2 'Node identity' row,
§5.7). The taint key keeps the virtual-kubelet convention with provider=tpu.
"""

from __future__ import annotations

from ..cloud.types import ACCELERATOR_CATALOG
from ..config import Config
from ..kube import objects as ko

TAINT_KEY = "virtual-kubelet.io/provider"
TAINT_VALUE = "tpu"
# degraded-node signaling (ISSUE 3): while the cloud-API circuit breaker is
# open (or the reconcile loop sees a sustained error streak), this NoSchedule
# taint stops the scheduler binding NEW pods to the node — existing bound
# pods keep reconciling from cache and are never failed merely because the
# API blinked. Removed (and TpuApiReachable flips back to True) when the
# half-open probe succeeds.
DEGRADED_TAINT_KEY = "tpu.dev/api-unreachable"
API_CONDITION = "TpuApiReachable"


def build_node(cfg: Config, *, cloud_healthy: bool = True,
               kubelet_port: int = 10250,
               quota_chips: int | None = None,
               api_reachable: bool = True) -> dict:
    """``google.com/tpu`` capacity/allocatable is the tightest of the live
    cloud quota (``quota_chips``, read periodically from the quota API by the
    provider) and the operator's configured ceiling ``cfg.max_total_chips``
    (still useful to reserve LESS than quota for this cluster). The K8s
    scheduler itself subtracts bound pods' requests from allocatable —
    the kubelet must NOT pre-decrement (that would double-count every
    bound chip) — so this one number is what bounds concurrently-bound
    chips: pods past it go Unschedulable instead of queueing invisibly
    in the cloud. Replaces the reference's static nvidia.com/gpu:4
    fiction (kubelet.go:1129); with neither signal available, falls back
    to the largest catalog slice."""
    # max_total_chips uses 0-means-unset (config default); a LIVE quota of 0
    # is a real answer — a project with no chip grant yet must advertise 0,
    # not fall back to catalog capacity and bind pods that can never deploy.
    # declared node pools (ISSUE 19): with fleet_pools set the node's chip
    # capacity is the pools' SUM (bounded below by quota/config like any
    # other ceiling) and each pool advertises itself as a label —
    # tpu.dev/pool.<name>=<generation>:<chips> — so operators and the
    # fleet scheduler see the same per-generation capacity split the
    # scheduler places against.
    pools = []
    if cfg.fleet_pools:
        from ..fleet.scheduler import parse_pools
        pools = parse_pools(cfg.fleet_pools)
    bounds = [c for c in (cfg.max_total_chips or None, quota_chips,
                          sum(p.total_chips for p in pools) or None)
              if c is not None]
    max_chips = min(bounds) if bounds else \
        max(a.chips for a in ACCELERATOR_CATALOG.values())
    generations = sorted({a.generation for a in ACCELERATOR_CATALOG.values()})
    ready = "True" if cloud_healthy else "False"
    now = ko.now_iso()
    conditions = [
        {"type": "Ready", "status": ready,
         "reason": "KubeletReady" if cloud_healthy else "CloudAPIUnreachable",
         "message": "virtual TPU kubelet is ready" if cloud_healthy
                    else "TPU API health check failing",
         "lastHeartbeatTime": now, "lastTransitionTime": now},
        {"type": "MemoryPressure", "status": "False", "reason": "KubeletHasSufficientMemory",
         "lastHeartbeatTime": now, "lastTransitionTime": now},
        {"type": "DiskPressure", "status": "False", "reason": "KubeletHasNoDiskPressure",
         "lastHeartbeatTime": now, "lastTransitionTime": now},
        {"type": "PIDPressure", "status": "False", "reason": "KubeletHasSufficientPID",
         "lastHeartbeatTime": now, "lastTransitionTime": now},
        {"type": API_CONDITION,
         "status": "True" if api_reachable else "False",
         "reason": "CloudAPIHealthy" if api_reachable else "CircuitOpen",
         "message": ("TPU API reachable" if api_reachable else
                     "TPU API circuit breaker open / sustained API errors — "
                     "new pods tainted away; bound pods keep reconciling"),
         "lastHeartbeatTime": now, "lastTransitionTime": now},
    ]
    taints = [{"key": TAINT_KEY, "value": TAINT_VALUE, "effect": "NoSchedule"}]
    if not api_reachable:
        taints.append({"key": DEGRADED_TAINT_KEY, "value": "true",
                       "effect": "NoSchedule"})
    capacity = {
        "cpu": "1000",          # a slice fleet's worth of host CPU
        "memory": "4Ti",
        "pods": "100",          # parity: kubelet.go:1133
        "google.com/tpu": str(max_chips),
    }
    allocatable = dict(capacity)  # scheduler subtracts bound pods itself
    labels = {
        "type": "virtual-kubelet",
        "kubernetes.io/role": "agent",
        "kubernetes.io/hostname": cfg.node_name,
        "kubernetes.io/os": cfg.operating_system.lower(),
        "node.kubernetes.io/instance-type": "cloud-tpu-slice",
        "tpu.dev/generations": "_".join(generations),
        "tpu.dev/default-generation": cfg.default_generation,
        "tpu.dev/zone": cfg.zone,
    }
    for pool in pools:
        # label VALUES may not contain ":", so generation and chips join
        # with "-" (e.g. tpu.dev/pool.v5e=v5e-32)
        labels[f"tpu.dev/pool.{pool.name}"] = \
            f"{pool.generation}-{pool.total_chips}"
    return {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": {
            "name": cfg.node_name,
            "labels": labels,
        },
        "spec": {
            "taints": taints,
        },
        "status": {
            "capacity": capacity,
            "allocatable": allocatable,
            "conditions": conditions,
            "addresses": [
                {"type": "InternalIP", "address": cfg.internal_ip},
                {"type": "Hostname", "address": cfg.node_name},
            ],
            "daemonEndpoints": {"kubeletEndpoint": {"Port": kubelet_port}},
            "nodeInfo": {
                "operatingSystem": cfg.operating_system.lower(),
                "architecture": "amd64",
                "kubeletVersion": "v1.29.0-tpu-virtual-kubelet",
            },
        },
    }
