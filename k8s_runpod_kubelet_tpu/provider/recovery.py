"""Crash recovery: the 3-way reconcile of K8s pods vs cloud slices.

Rebuild of LoadRunning + friends (kubelet.go:1380-1796). The durable state is
(a) the tpu.dev/queued-resource-id pod annotation and (b) the cloud's list API
with pod-identity labels; the in-memory maps are caches this module rebuilds on
startup (SURVEY.md §3.5, §5.4).

Orphan adoption (CreateVirtualPod analog, kubelet.go:1564-1634) deliberately
fixes the reference's node-name bug: adopted pods land on cfg.node_name, not a
hard-coded string that differs from the running node (SURVEY.md §2 row 8 notes
the "runpod-virtual-node" vs "virtual-runpod" mismatch).
"""

from __future__ import annotations

import logging

from ..cloud.tpu_client import TpuApiError
from ..cloud.types import QueuedResource, QueuedResourceState as S
from ..kube.client import KubeApiError
from ..kube import objects as ko
from .annotations import Annotations as A
from .status import status_fingerprint

log = logging.getLogger(__name__)


def _recovery_announced(pod: dict) -> bool:
    """Was the CURRENT preemption attempt's RecoveredFromPreemption already
    emitted (pre-restart)? The durable tpu.dev/recovered-attempt marker
    equals tpu.dev/preemption-count exactly when it was — so a restart
    neither duplicates an announced recovery nor swallows a pending one."""
    anns = ko.annotations(pod)
    count = anns.get(A.PREEMPTION_COUNT, "")
    return bool(count) and anns.get(A.RECOVERED_ATTEMPT, "") == count


def _elastic_state(pod: dict) -> tuple[int, tuple, "int | None"]:
    """(resize_count, lost_workers, resize_step) from the durable
    annotations — a kubelet restart mid-shrink must neither forget the
    exclusion (the dead worker would GangBroken-fail the pod) nor re-shrink
    an already-shrunk gang (double-bumping resize-count and restarting the
    survivors for nothing); resize_step keeps the grow path's
    checkpoint-boundary check honest (a PRE-shrink checkpoint log line must
    not pass for a fresh boundary after the restart)."""
    anns = ko.annotations(pod)
    try:
        count = int(anns.get(A.RESIZE_COUNT, "0") or 0)
    except ValueError:
        count = 0
    lost = []
    for tok in (anns.get(A.LOST_WORKERS, "") or "").split(","):
        tok = tok.strip()
        if tok.isdigit():
            lost.append(int(tok))
    step_s = anns.get(A.RESIZE_STEP, "")
    step = int(step_s) if step_s.isdigit() else None
    return count, tuple(sorted(lost)), step


class RecoveryMixin:
    def load_running(self):
        """Startup state recovery (parity: LoadRunning kubelet.go:1380-1535)."""
        try:
            pods = self.kube.list_pods(
                field_selector=f"spec.nodeName={self.cfg.node_name}")
        except KubeApiError as e:
            log.error("recovery: cannot list pods: %s", e)
            return
        try:
            slices = {qr.name: qr for qr in self.tpu.list_queued_resources()
                      if qr.labels.get("managed-by") == "tpu-virtual-kubelet"
                      and qr.labels.get("node") == self.cfg.node_name}
            slices_listed = True
        except TpuApiError as e:
            # A transient list failure must NOT make bound slices look missing —
            # that would strip bindings and Fail healthy pods. Recover what the
            # annotations alone allow; the reconcile loop completes the picture.
            log.error("recovery: cannot list slices: %s — recovering by "
                      "annotation only, skipping missing-slice handling", e)
            slices = {}
            slices_listed = False

        now = self.clock()
        claimed: set[str] = set()
        recovered = adopted = pending = missing = 0
        for pod in pods:
            key = ko.namespaced_name(pod)
            if ko.is_terminal(pod):
                continue  # kubelet.go:1419-1427
            with self.lock:
                if key in self.instances and self.instances[key].qr_name:
                    continue  # already tracked (:1440-1446)
            if ko.deletion_timestamp(pod):
                # terminating: let the stuck-terminating ladder handle it
                qr_name = ko.annotations(pod).get(A.QUEUED_RESOURCE, "")
                if qr_name:
                    claimed.add(qr_name)
                continue
            qr_name = ko.annotations(pod).get(A.QUEUED_RESOURCE, "")
            if not qr_name:
                # match by the slice's pod-uid label (covers a crash between
                # create and annotate — stronger than the reference)
                for qr in slices.values():
                    if qr.labels.get("pod-uid") == ko.uid(pod):
                        qr_name = qr.name
                        break
            try:
                if qr_name and qr_name in slices:
                    self._recover_instance(pod, slices[qr_name])
                    claimed.add(qr_name)
                    recovered += 1
                elif qr_name and slices_listed:
                    self.handle_missing_instance(pod)  # :1484-1487
                    missing += 1
                elif qr_name:
                    # list failed: trust the annotation, let reconcile verify
                    self._recover_by_annotation(pod, qr_name)
                    claimed.add(qr_name)
                    recovered += 1
                else:
                    with self.lock:  # no slice: pending processor deploys (:1488-1506)
                        from .provider import InstanceInfo
                        self.pods[key] = ko.deep_copy(pod)
                        self.instances[key] = InstanceInfo(created_at=now,
                                                           pending_since=now)
                    pending += 1
            except TpuApiError as e:
                # one pod's cloud hiccup must not abort recovery of the rest;
                # the reconcile loop retries this pod every cycle anyway
                log.warning("recovery of %s failed (%s) — deferring to the "
                            "reconcile loop", key, e)
                self._recover_by_annotation(pod, qr_name)
                if qr_name:
                    # still claimed: the orphan loop must not adopt or delete
                    # the slice of a pod we just re-bound
                    claimed.add(qr_name)

        # orphan adoption: slices with no K8s pod (:1510-1524)
        for qr in slices.values():
            if qr.name in claimed:
                continue
            if qr.state in (S.ACTIVE, S.ACCEPTED, S.WAITING_FOR_RESOURCES, S.PROVISIONING):
                if self.create_virtual_pod(qr):
                    adopted += 1
            else:
                log.info("recovery: terminal orphan slice %s (%s) — deleting",
                         qr.name, qr.state.value)
                try:
                    self.tpu.delete_queued_resource(qr.name, zone=qr.zone or None)
                except TpuApiError as e:
                    log.warning("recovery: delete orphan %s failed: %s", qr.name, e)
        log.info("recovery complete: %d recovered, %d adopted, %d pending, "
                 "%d missing-slice", recovered, adopted, pending, missing)

    def _recover_by_annotation(self, pod: dict, qr_name: str):
        """Minimal re-bind when the cloud can't be consulted: cache the pod with
        its annotated slice; the reconcile loop fills in live state (or routes
        to handle_missing_instance if the slice really is gone)."""
        from .provider import InstanceInfo
        if not qr_name:
            return
        key = ko.namespaced_name(pod)
        resize_count, lost_workers, resize_step = _elastic_state(pod)
        with self.lock:
            self.pods[key] = ko.deep_copy(pod)
            self.instances[key] = InstanceInfo(
                qr_name=qr_name,
                zone=ko.annotations(pod).get(A.ZONE, "") or self.cfg.zone,
                accelerator_type=ko.annotations(pod).get(A.ACCELERATOR_TYPE, ""),
                created_at=self.clock(),
                trace_id=ko.annotations(pod).get(A.TRACE_ID, ""),
                preemption_count=int(
                    ko.annotations(pod).get(A.PREEMPTION_COUNT, "0") or 0),
                recovery_event_emitted=_recovery_announced(pod),
                resize_count=resize_count,
                lost_workers=lost_workers,
                resize_step=resize_step,
                # the shrink time didn't survive the restart: restart the
                # grow grace from now rather than growing immediately
                resized_at=self.clock() if lost_workers else None,
            )

    def _recover_instance(self, pod: dict, qr: QueuedResource):
        """Rebuild the cache entry from a live slice (kubelet.go:1455-1483)."""
        from .provider import InstanceInfo
        key = ko.namespaced_name(pod)
        acc = qr.accelerator
        detailed = self.tpu.get_detailed_status(qr.name, zone=qr.zone or self.cfg.zone)
        resize_count, lost_workers, resize_step = _elastic_state(pod)
        info = InstanceInfo(
            qr_name=qr.name,
            zone=qr.zone or self.cfg.zone,
            status=qr.state,
            accelerator_type=qr.accelerator_type,
            cost_per_hr=acc.cost_per_hr if acc else 0.0,
            workload_launched=bool(detailed.runtime),
            created_at=qr.create_time or self.clock(),
            # keep the lifecycle trace joinable across kubelet restarts
            trace_id=ko.annotations(pod).get(A.TRACE_ID, ""),
            # the requeue budget survives restarts too: a pod on its 2nd
            # requeue must not get a fresh allowance (and its recovery
            # event keeps the true attempt number)
            preemption_count=int(
                ko.annotations(pod).get(A.PREEMPTION_COUNT, "0") or 0),
            recovery_event_emitted=_recovery_announced(pod),
            # elastic state survives too: a restart mid-shrink must not
            # re-shrink (or GangBroken-fail) an already-resized gang
            resize_count=resize_count,
            lost_workers=lost_workers,
            resize_step=resize_step,
            resized_at=self.clock() if lost_workers else None,
        )
        with self.lock:
            self.pods[key] = ko.deep_copy(pod)
            self.instances[key] = info
        log.info("recovery: pod %s re-bound to slice %s (%s, launched=%s)",
                 key, qr.name, qr.state.value, info.workload_launched)

    def create_virtual_pod(self, qr: QueuedResource) -> bool:
        """Adopt an orphan slice as a virtual pod so it is visible and
        GC-able in K8s (parity: CreateVirtualPod kubelet.go:1564-1634)."""
        from .provider import InstanceInfo
        ns = qr.labels.get("pod-namespace") or self.cfg.namespace
        name = qr.labels.get("pod-name") or f"adopted-{qr.name}"
        image = "adopted/unknown"
        pod = {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": name,
                "namespace": ns,
                "annotations": {
                    A.QUEUED_RESOURCE: qr.name,
                    A.ZONE: qr.zone or self.cfg.zone,
                    A.ACCELERATOR_TYPE: qr.accelerator_type,
                    A.EXTERNAL: "true",  # adoption marker (kubelet.go:1580)
                },
                "labels": {"tpu.dev/adopted": "true"},
            },
            "spec": {
                "nodeName": self.cfg.node_name,  # the running node — NOT hard-coded
                "containers": [{"name": "workload", "image": image}],
                "tolerations": [{"key": "virtual-kubelet.io/provider",
                                 "operator": "Exists"}],
                "restartPolicy": "Never",
            },
        }
        try:
            created = self.kube.create_pod(pod)
        except KubeApiError as e:
            log.warning("adoption of %s failed: %s", qr.name, e)
            return False
        key = ko.namespaced_name(created)
        acc = qr.accelerator
        with self.lock:
            self.pods[key] = created
            self.instances[key] = InstanceInfo(
                qr_name=qr.name, zone=qr.zone or self.cfg.zone, status=qr.state,
                accelerator_type=qr.accelerator_type,
                cost_per_hr=acc.cost_per_hr if acc else 0.0,
                workload_launched=True,  # it is running something we didn't start
                created_at=qr.create_time or self.clock(),
            )
        log.info("adopted orphan slice %s as pod %s", qr.name, key)
        return True

    def handle_missing_instance(self, pod: dict):
        """Slice vanished: strip binding annotations, mark Failed
        (parity: handleMissingRunPodInstance kubelet.go:1708-1773)."""
        key = ko.namespaced_name(pod)
        log.warning("slice for pod %s no longer exists — marking Failed", key)
        try:
            self.kube.patch_pod(ko.namespace(pod), ko.name(pod), {
                "metadata": {"annotations": {
                    A.QUEUED_RESOURCE: None, A.COST_PER_HR: None, A.ZONE: None}}})
        except KubeApiError as e:
            if not e.is_not_found:
                log.warning("strip annotations of %s failed: %s", key, e)
        status = {
            "phase": "Failed", "reason": "SliceNotFound",
            "message": "backing TPU slice no longer exists "
                       "(preempted and deleted, or removed out-of-band)",
            "conditions": [{"type": "Ready", "status": "False",
                            "reason": "SliceNotFound"}],
        }
        with self.lock:
            info = self.instances.get(key)
            if info:
                info.pod_status = status
                info.fingerprint = status_fingerprint(status)
                info.status = S.NOT_FOUND
        self._push_status(key, pod, status)
        self.metrics.incr("tpu_kubelet_missing_slices")

    def force_delete_pod(self, pod: dict):
        """Grace-0 delete (parity: ForceDeletePod kubelet.go:1776-1796)."""
        self.emit_event(pod, "ForceDeleted",
                        "stuck terminating — force deleting with grace 0",
                        event_type="Warning")
        try:
            self.kube.delete_pod(ko.namespace(pod), ko.name(pod), grace_period_s=0)
        except KubeApiError as e:
            if not e.is_not_found:
                log.warning("force delete %s failed: %s", ko.namespaced_name(pod), e)
        key = ko.namespaced_name(pod)
        with self.lock:
            self.pods.pop(key, None)
            self.instances.pop(key, None)
            # clear unreachable tracking on every exit from the stuck ladder,
            # else a later same-named pod inherits a stale timestamp and gets
            # force-deleted without its 10-minute grace
            self._stuck_unreachable.pop(key, None)
