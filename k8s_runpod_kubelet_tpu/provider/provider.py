"""The Provider: pod caches, lifecycle handlers, deploy path, node identity.

Rebuild of the reference Provider (kubelet.go:27-731), TPU-native:

- CreatePod caches + deploys; a deploy failure leaves the pod Pending for the
  pending processor to retry (parity: kubelet.go:412-415).
- Deploy is two-phase on TPU: (1) create the queued resource at CreatePod time,
  (2) gang-launch the workload with per-worker env once the slice turns ACTIVE
  (reconcile.py) — RunPod had no phase 2 because one instance boots one
  container; a slice is N bare VMs that must start together.
- The durable pod<->slice binding is the tpu.dev/queued-resource-id annotation
  plus the cloud list API; in-memory maps are caches rebuilt by recovery.py
  (state model parity: SURVEY.md §5.4).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Callable, Optional

from ..cloud.tpu_client import (NotFoundError, QuotaError, TpuApiError, TpuClient)
from ..cloud.types import DetailedStatus, QueuedResourceState as S
from ..config import Config
from ..gang import GangExecutor
from ..kube.client import KubeApiError, KubeClient
from ..kube import objects as ko
from ..metrics import Metrics
from ..tracing import Tracer
from .annotations import Annotations as A
from .elastic import ElasticGangMixin
from .node_spec import build_node
from .reconcile import ReconcileMixin
from .recovery import RecoveryMixin
from .training_watch import TrainingWatchMixin
from .translate import TranslationError, prepare_tpu_parameters

log = logging.getLogger(__name__)

HEALTH_PROBE_MIN_INTERVAL_S = 10.0
# Quota moves on human timescales (support tickets); re-read it on a slow
# multiple of the health probe so capacity tracks grants without a restart.
QUOTA_PROBE_MIN_INTERVAL_S = 300.0


@dataclasses.dataclass
class InstanceInfo:
    """Cache entry for one pod's slice (analog of the reference's InstanceInfo,
    kubelet.go:391-401)."""

    qr_name: str = ""
    zone: str = ""
    status: Optional[S] = None
    accelerator_type: str = ""
    cost_per_hr: float = 0.0
    workload_launched: bool = False
    ready: bool = False
    pod_status: Optional[dict] = None       # last translated v1.PodStatus
    fingerprint: tuple = ()
    # pending-deploy bookkeeping (kubelet.go:747-814)
    pending_since: Optional[float] = None
    last_deploy_error: str = ""
    # when the CURRENT slice's queued resource was created (reset on
    # preemption requeue): the pod.provisioning span must time the current
    # attempt's cloud wait, not the pod's whole life since schedule
    deployed_at: Optional[float] = None
    # north-star latency timestamps
    created_at: float = 0.0
    active_at: Optional[float] = None
    launched_at: Optional[float] = None
    ready_at: Optional[float] = None
    preemption_count: int = 0
    # checkpoint-aware preemption recovery (ISSUE 3): set when this attempt's
    # RecoveredFromPreemption event/span has been emitted (reset on requeue so
    # every recovery announces itself exactly once)
    recovery_event_emitted: bool = False
    # elastic gang resizing (ISSUE 6): cumulative shrink/grow count (NEVER
    # counted against preemption_requeue_limit), the worker ids currently
    # excluded from the gang (non-empty = running shrunk), when the last
    # resize happened, and the scraped step at that moment (the grow path
    # prefers a checkpoint NEWER than this). resize_count/lost_workers are
    # mirrored to tpu.dev/resize-count / tpu.dev/lost-workers and restored
    # by recovery.py across kubelet restarts.
    resize_count: int = 0
    lost_workers: tuple = ()
    resized_at: Optional[float] = None
    resize_step: Optional[int] = None
    # training telemetry (ISSUE 5): the reconcile loop's scrape of worker-0's
    # TPU_TELEMETRY line. train_step_at is when the step counter last
    # ADVANCED (the stall clock); train_annotated is the last annotation
    # fingerprint patched (no per-sweep patch spam); train_stalled marks an
    # announced stall episode (one TrainingStalled event per episode)
    train_last_step: Optional[int] = None
    train_step_at: Optional[float] = None
    train_stalled: bool = False
    train_annotated: tuple = ()
    # scrape backoff: when the first probe happened, and the last one —
    # a pod that never emits telemetry (serving) drops to a slow probe
    # cadence instead of paying a log fetch every sweep forever
    train_first_probe_at: Optional[float] = None
    train_probe_at: Optional[float] = None
    # lifecycle tracing: all of this pod's spans share trace_id (also
    # annotated on the pod as tpu.dev/trace-id); trace_root is the
    # pod.lifecycle root span id the phase spans parent under — derived
    # DETERMINISTICALLY as trace_id[:16] so spans recorded before and
    # after a kubelet restart (recovery restores only the trace_id) still
    # parent under the same root
    trace_id: str = ""
    trace_root: str = ""


@dataclasses.dataclass
class DeletedPodInfo:
    """Tracks a deleted pod until its slice is confirmed gone
    (analog: deletedPods map, kubelet.go:628-631)."""

    qr_name: str
    zone: str
    deleted_at: float
    last_terminate_at: float = 0.0
    unreachable_since: Optional[float] = None


class Provider(ReconcileMixin, RecoveryMixin, TrainingWatchMixin,
               ElasticGangMixin):
    def __init__(self, cfg: Config, kube: KubeClient, tpu: TpuClient,
                 gang_executor: Optional[GangExecutor] = None,
                 metrics: Optional[Metrics] = None,
                 clock: Callable[[], float] = time.time,
                 tracer: Optional[Tracer] = None):
        self.cfg = cfg
        self.kube = kube
        self.tpu = tpu
        self.gang = gang_executor
        self.clock = clock
        self.metrics = metrics or Metrics()
        # pod-lifecycle spans (deploy/provisioning/gang-launch/ready) share
        # the injected clock so FakeClock tests see honest durations.
        # `is None`, not `or`: an injected EMPTY tracer is falsy (len 0)
        # and `or` would silently disconnect it from the health server
        self.tracer = tracer if tracer is not None else Tracer(clock=clock)

        self.lock = threading.RLock()
        self._reconcile_guard = threading.Lock()  # one reconcile pass at a time
        self.pods: dict[str, dict] = {}                 # ns/name -> pod
        self.instances: dict[str, InstanceInfo] = {}    # ns/name -> info
        self.deleted: dict[str, DeletedPodInfo] = {}    # ns/name -> tombstone
        # stuck-terminating pods whose slice status is erroring (non-404):
        # ns/name -> first-unreachable timestamp (see reconcile.py ladder)
        self._stuck_unreachable: dict[str, float] = {}

        self._notify_cb: Optional[Callable[[dict], None]] = None
        self._node_status_cb: Optional[Callable[[], None]] = None
        self._cloud_healthy = True
        self._last_health_probe = 0.0
        # degraded-node signaling (ISSUE 3): the breaker (when the transport
        # has one) plus the reconcile loop's own consecutive-API-error streak
        # both feed api_reachable; either flips the TpuApiReachable condition
        # and the NoSchedule taint
        self._api_error_streak = 0
        self._breaker = getattr(tpu, "breaker", None)
        if self._breaker is not None:
            self._breaker.on_state_change = self._on_breaker_change
        # fleet scheduler (ISSUE 19): with declared node pools the
        # training watch feeds measured MFU + unsaved-work into the
        # scheduler's throughput matrix / preemption-cost estimates.
        # Embedding processes (router_main-in-kubelet setups, the soak)
        # may inject a SHARED instance instead.
        self.fleet_scheduler = None
        if cfg.fleet_pools:
            from ..fleet.scheduler import FleetScheduler
            self.fleet_scheduler = FleetScheduler(
                cfg.fleet_pools, metrics=self.metrics, tracer=self.tracer,
                clock=clock)
        self._chip_quota: Optional[int] = None   # live cloud quota, if readable
        self._last_quota_probe = 0.0
        self._quota_probe_failing = False        # warn once per failure streak
        self._quota_none_streak = 0              # consecutive empty reads
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

        self.metrics.describe("tpu_kubelet_schedule_to_ready_seconds",
                              "pod bound -> gang running (north-star latency)")
        self.metrics.describe("tpu_kubelet_schedule_to_active_seconds",
                              "pod bound -> slice ACTIVE")
        self.metrics.describe("tpu_kubelet_deploys", "queued-resource create attempts")
        self.metrics.describe("tpu_kubelet_cloud_healthy",
                              "TPU API health probe result (1 = healthy)")
        self.metrics.describe("tpu_kubelet_chip_quota",
                              "live cloud chip quota (-1 = unreadable)")
        self.metrics.describe("tpu_kubelet_slices_released",
                              "slices deleted after their pod went terminal")
        self.metrics.describe("tpu_kubelet_preemption_requeues",
                              "preempted slices resubmitted instead of failed")
        self.metrics.describe("tpu_kubelet_gang_launches",
                              "all-worker workload launches on ACTIVE slices")
        self.metrics.describe("tpu_kubelet_missing_slices",
                              "pods whose slice vanished out from under them")
        self.metrics.describe("tpu_kubelet_loop_seconds",
                              "background control-loop iteration latency")
        self.metrics.describe("tpu_kubelet_api_degraded",
                              "node degraded: breaker open or sustained API "
                              "errors (1 = TpuApiReachable=False + taint)")
        self.metrics.describe("tpu_kubelet_preemption_recoveries",
                              "requeued pods that came back Ready "
                              "(RecoveredFromPreemption)")
        self._describe_training_metrics()
        self._describe_elastic_metrics()
        self._probe_cloud(force=True)

    # -- helpers ---------------------------------------------------------------

    @staticmethod
    def key_of(pod: dict) -> str:
        return ko.namespaced_name(pod)

    def emit_event(self, pod: dict, reason: str, message: str,
                   event_type: str = "Normal"):
        """Broadcast a K8s event on the pod so `kubectl describe pod` shows the
        lifecycle trail (parity: the reference's event recorder,
        main.go:172-177). Event failures never disrupt the control loop."""
        ns = ko.namespace(pod)
        ts = ko.now_iso(self.clock())
        event = {
            "metadata": {"generateName": f"{ko.name(pod)}.", "namespace": ns},
            "type": event_type,
            "reason": reason,
            "message": message,
            "involvedObject": {"kind": "Pod", "namespace": ns,
                               "name": ko.name(pod),
                               "uid": ko.meta(pod).get("uid", "")},
            "source": {"component": "tpu-virtual-kubelet",
                       "host": self.cfg.node_name},
            "firstTimestamp": ts, "lastTimestamp": ts, "count": 1,
        }
        try:
            self.kube.create_event(ns, event)
        except KubeApiError as e:
            log.debug("event %s on %s failed: %s", reason, self.key_of(pod), e)

    @property
    def api_reachable(self) -> bool:
        """Degraded-node signal (ISSUE 3): False while the cloud-API circuit
        breaker is open/half-open OR the reconcile loop has seen a sustained
        streak of API errors. Drives the TpuApiReachable node condition, the
        tpu.dev/api-unreachable NoSchedule taint, and /readyz. Heals (True)
        the moment the half-open probe succeeds / a cloud call works."""
        if self._breaker is not None:
            from ..cloud.transport import CLOSED
            if self._breaker.state != CLOSED:
                return False
        return self._api_error_streak < self.cfg.breaker_failure_threshold

    def _on_breaker_change(self, old: int, new: int):
        """Breaker state flipped (fired by the transport outside its lock):
        reflect it on the node immediately — don't wait for the 30s status
        loop to notice the scheduler is binding pods into a black hole."""
        from ..cloud.transport import CLOSED
        if new == CLOSED:
            self._api_error_streak = 0
        self.metrics.set_gauge("tpu_kubelet_api_degraded",
                               0.0 if self.api_reachable else 1.0)
        self._notify_node_status()

    def note_api_result(self, ok: bool):
        """Reconcile-loop API outcome accounting: a consecutive-error streak
        crossing the threshold degrades the node even when no breaker is
        wired (e.g. errors that never hit the shared transport)."""
        was = self.api_reachable
        if ok:
            self._api_error_streak = 0
        else:
            self._api_error_streak += 1
        now_reachable = self.api_reachable
        if was != now_reachable:
            log.warning("TPU API degraded-state changed: reachable=%s "
                        "(error streak %d)", now_reachable,
                        self._api_error_streak)
            self.metrics.set_gauge("tpu_kubelet_api_degraded",
                                   0.0 if now_reachable else 1.0)
            self._notify_node_status()

    def _probe_cloud(self, force: bool = False) -> bool:
        """Rate-limited cloud health probe (parity: checkRunPodAPIHealth
        kubelet.go:320-331, re-probed by Ping :1070-1076)."""
        now = self.clock()
        if force or now - self._last_health_probe >= HEALTH_PROBE_MIN_INTERVAL_S:
            self._last_health_probe = now
            healthy = self.tpu.health_check()
            if healthy != self._cloud_healthy:
                log.warning("TPU API health changed: %s -> %s", self._cloud_healthy, healthy)
                self._cloud_healthy = healthy
                self._notify_node_status()
            self.metrics.set_gauge("tpu_kubelet_cloud_healthy", 1.0 if healthy else 0.0)
            if healthy:
                # a successful probe is proof of reachability: heal the
                # reconcile-loop error streak even when no pods reconcile
                if self._api_error_streak:
                    self.note_api_result(True)
                self._refresh_chip_quota(now, force=force)
        return self._cloud_healthy

    def _refresh_chip_quota(self, now: float, force: bool = False):
        """Track the project's live chip quota so node capacity follows grants
        (closes VERDICT r3 weak-6: max_total_chips was an operator constant the
        quota could silently drift away from). Quota-API failures keep the
        last-known value — a flaky quota read must not flap node capacity."""
        if not force and now - self._last_quota_probe < QUOTA_PROBE_MIN_INTERVAL_S:
            return
        self._last_quota_probe = now
        try:
            # scope the read to this node's DEFAULT generation: its
            # google.com/tpu capacity must reflect the grant its slices
            # draw on, not the project-wide sum over generations (ADVICE
            # r4). Known residual: a pod overriding generation via the
            # tpu.dev/generation annotation draws on a DIFFERENT grant
            # than the advertised capacity and can still fail at
            # provision time — exact per-generation admission would need
            # per-generation extended resources, which upstream K8s
            # device accounting doesn't give a virtual node.
            quota = self.tpu.get_chip_quota(
                generation=self.cfg.default_generation)
        except TpuApiError as e:
            # keep last-known capacity (anti-flap) but make the failure
            # visible: warn on the first consecutive failure, and mark the
            # gauge unreadable so a stale number can't outlive its read
            level = log.debug if self._quota_probe_failing else log.warning
            level("chip quota probe failed (capacity keeps %s): %s",
                  self._chip_quota, e)
            self._quota_probe_failing = True
            self.metrics.set_gauge("tpu_kubelet_chip_quota", -1.0)
            return
        self._quota_probe_failing = False
        if quota is None and self._chip_quota is not None:
            # None can mean "quota surface gone" OR a transient 403 (IAM
            # propagation, auth blip) — the client maps both to None. Don't
            # let one blip inflate capacity to the ceiling/catalog fallback;
            # require consecutive None reads before dropping a known quota.
            self._quota_none_streak += 1
            if self._quota_none_streak < 2:
                log.warning("quota read returned no data (keeping %s, "
                            "dropping after another miss)", self._chip_quota)
                self.metrics.set_gauge("tpu_kubelet_chip_quota", -1.0)
                return
        else:
            self._quota_none_streak = 0
        if quota != self._chip_quota:
            log.info("cloud chip quota: %s -> %s", self._chip_quota, quota)
            self._chip_quota = quota
            self._notify_node_status()
        # -1 = quota unreadable/unlimited, so a stale numeric value can't
        # outlive the condition it measured
        self.metrics.set_gauge("tpu_kubelet_chip_quota",
                               float(quota) if quota is not None else -1.0)

    def _notify_node_status(self):
        cb = self._node_status_cb
        if cb:
            try:
                cb()
            except Exception as e:  # noqa: BLE001
                log.warning("node status notify failed: %s", e)

    # -- PodLifecycleHandler (called by node/pod_controller) -------------------

    def create_pod(self, pod: dict):
        """Cache + deploy. Deploy failure is NOT an error: the pod stays Pending
        and the pending processor retries (parity: kubelet.go:384-418)."""
        key = self.key_of(pod)
        now = self.clock()
        with self.lock:
            self.pods[key] = ko.deep_copy(pod)
            info = self.instances.get(key) or InstanceInfo()
            info.created_at = info.created_at or now
            info.pending_since = info.pending_since or now
            if not info.trace_id:
                # a re-created pod carrying the annotation keeps its trace
                # (the spans join up across kubelet restarts)
                info.trace_id = (ko.annotations(pod).get(A.TRACE_ID)
                                 or Tracer.new_trace_id())
            info.trace_root = info.trace_root or info.trace_id[:16]
            self.instances[key] = info
        log.info("CreatePod %s", key)
        self.deploy_pod(pod)

    def update_pod(self, pod: dict):
        key = self.key_of(pod)
        with self.lock:
            if key in self.pods:
                self.pods[key] = ko.deep_copy(pod)

    def delete_pod(self, pod: dict):
        """Terminate the slice, tombstone for GC, drop caches, then confirm the
        K8s deletion with a grace-0 delete (parity: kubelet.go:621-651; the K8s
        removal is ours to do since we ARE the L3 controller layer)."""
        key = self.key_of(pod)
        with self.lock:
            info = self.instances.get(key)
            qr_name = info.qr_name if info else \
                ko.annotations(pod).get(A.QUEUED_RESOURCE, "")
            zone = info.zone if info and info.zone else self.cfg.zone
            if qr_name:
                self.deleted[key] = DeletedPodInfo(
                    qr_name=qr_name, zone=zone, deleted_at=self.clock())
        log.info("DeletePod %s (slice=%s)", key, qr_name or "<none>")
        self._clear_training_gauges(key)
        if qr_name:
            try:
                self.tpu.delete_queued_resource(qr_name, zone=zone)
            except TpuApiError as e:
                log.warning("terminate %s failed (cleanup loop will retry): %s",
                            qr_name, e)
        with self.lock:
            self.pods.pop(key, None)
            self.instances.pop(key, None)
        try:
            ns, name = key.split("/", 1)
            self.kube.delete_pod(ns, name, grace_period_s=0)
        except KubeApiError as e:
            if not e.is_not_found:
                log.warning("grace-0 delete of %s failed: %s", key, e)

    def get_pod(self, ns: str, name: str) -> Optional[dict]:
        with self.lock:
            return ko.deep_copy(self.pods.get(f"{ns}/{name}"))

    def get_pod_status(self, ns: str, name: str) -> Optional[dict]:
        with self.lock:
            info = self.instances.get(f"{ns}/{name}")
            if info and info.pod_status:
                return ko.deep_copy(info.pod_status)
            pod = self.pods.get(f"{ns}/{name}")
            return ko.deep_copy(pod.get("status", {})) if pod else None

    def get_pods(self) -> list[dict]:
        with self.lock:
            return [ko.deep_copy(p) for p in self.pods.values()]

    def notify_pods(self, callback: Callable[[dict], None]):
        """Register the async status-change callback
        (parity: NotifyPods kubelet.go:713-731)."""
        self._notify_cb = callback

    # -- deploy ----------------------------------------------------------------

    def deploy_pod(self, pod: dict) -> bool:
        """Create the queued resource and annotate the pod with the binding.
        Returns True if the slice exists after the call."""
        key = self.key_of(pod)
        if not self._probe_cloud():
            log.warning("skipping deploy of %s: TPU API unhealthy "
                        "(parity: kubelet.go:458-460)", key)
            return False
        self.metrics.incr("tpu_kubelet_deploys")
        deploy_started = self.clock()
        try:
            params = prepare_tpu_parameters(self.kube, pod, self.cfg)
        except TranslationError as e:
            with self.lock:
                info = self.instances.get(key)
                if info:
                    info.last_deploy_error = str(e)
            log.warning("cannot translate pod %s: %s", key, e)
            return False

        try:
            qr = self.tpu.create_queued_resource(params)
        except TpuApiError as e:
            if e.status == 409:
                # our deterministic name already exists — adopt it (idempotent
                # retry after a crash between create and annotate)
                try:
                    qr = self.tpu.get_queued_resource(params.name, zone=params.zone)
                except TpuApiError as e2:
                    log.error("deploy %s: conflict but fetch failed: %s", key, e2)
                    return False
            else:
                with self.lock:
                    info = self.instances.get(key)
                    if info:
                        info.last_deploy_error = str(e)
                lvl = logging.INFO if isinstance(e, QuotaError) else logging.WARNING
                log.log(lvl, "deploy %s failed: %s", key, e)
                self.emit_event(pod, "DeployFailed",
                                f"creating queued resource failed: {e}",
                                event_type="Warning")
                return False

        acc = qr.accelerator
        cost = acc.cost_per_hr if acc else 0.0
        with self.lock:
            info = self.instances.setdefault(key, InstanceInfo())
            info.qr_name = qr.name
            info.zone = params.zone
            info.status = qr.state
            info.accelerator_type = qr.accelerator_type
            info.cost_per_hr = cost
            info.pending_since = None
            info.last_deploy_error = ""
            info.deployed_at = self.clock()
            if not info.trace_id:  # deploy without create_pod (tests/tools)
                info.trace_id = Tracer.new_trace_id()
            info.trace_root = info.trace_root or info.trace_id[:16]
            trace_id, trace_root = info.trace_id, info.trace_root
        self.tracer.record("pod.deploy", deploy_started, self.clock(),
                           trace_id=trace_id, parent_id=trace_root,
                           attrs={"pod": key, "slice": qr.name,
                                  "accelerator": qr.accelerator_type,
                                  "zone": params.zone})
        self._annotate_binding(pod, qr.name, params.zone, qr.accelerator_type, cost)
        log.info("deployed %s -> slice %s (%s, $%.2f/hr, state %s)",
                 key, qr.name, qr.accelerator_type, cost, qr.state.value)
        self.emit_event(pod, "SliceCreated",
                        f"created queued resource {qr.name} "
                        f"({qr.accelerator_type}, ${cost:.2f}/hr)")
        return True

    def _annotate_binding(self, pod: dict, qr_name: str, zone: str,
                          accelerator: str, cost: float):
        """Write the durable binding annotations
        (parity: updatePodWithRunPodInfo kubelet.go:505-562)."""
        with self.lock:
            info = self.instances.get(self.key_of(pod))
            trace_id = info.trace_id if info else ""
        anns = {
            A.QUEUED_RESOURCE: qr_name,
            A.ZONE: zone,
            A.ACCELERATOR_TYPE: accelerator,
            A.COST_PER_HR: f"{cost:.4f}",
        }
        if trace_id:
            # the durable join key: a serving request on this slice sends
            # this as its traceparent trace id to land in the same tree as
            # the provisioning spans
            anns[A.TRACE_ID] = trace_id
        patch = {"metadata": {"annotations": anns}}
        try:
            updated = self.kube.patch_pod(ko.namespace(pod), ko.name(pod), patch)
            with self.lock:
                self.pods[self.key_of(pod)] = updated
        except KubeApiError as e:
            # cache still holds the binding; recovery can re-derive it from the
            # slice's pod-uid label even if this patch never lands
            log.warning("annotate %s failed: %s", self.key_of(pod), e)

    # -- NodeProvider ----------------------------------------------------------

    def get_node(self) -> dict:
        return build_node(self.cfg, cloud_healthy=self._cloud_healthy,
                          kubelet_port=self.cfg.listen_port,
                          quota_chips=self._chip_quota,
                          api_reachable=self.api_reachable)

    def ping(self) -> bool:
        # /readyz reflects degradation: an open breaker or a sustained API
        # error streak makes the node not-ready even while the rate-limited
        # health probe still remembers a healthy answer
        return self._probe_cloud() and self.api_reachable

    def set_status_listener(self, cb: Callable[[], None]):
        self._node_status_cb = cb

    # -- kubelet API (logs/exec — real, unlike the reference's stubs) ----------

    def _qr_for(self, ns: str, name: str):
        with self.lock:
            info = self.instances.get(f"{ns}/{name}")
        if not info or not info.qr_name:
            raise KeyError(f"pod {ns}/{name} has no slice")
        return self.tpu.get_queued_resource(info.qr_name, zone=info.zone)

    def get_container_logs(self, ns: str, name: str, container: str,
                           tail_lines: Optional[int] = None,
                           worker: Optional[int] = None) -> str:
        if self.gang is None:
            return "<no worker transport configured>\n"
        try:
            qr = self._qr_for(ns, name)
        except (NotFoundError,) as e:
            raise KeyError(str(e)) from e
        return self.gang.logs(qr, worker_id=worker, tail_lines=tail_lines)

    def run_in_container(self, ns: str, name: str, container: str,
                         cmd: list[str], worker: int = 0) -> str:
        if self.gang is None:
            raise NotImplementedError("no worker transport configured")
        try:
            qr = self._qr_for(ns, name)
        except (NotFoundError,) as e:
            raise KeyError(str(e)) from e
        return self.gang.run_on_worker(qr, worker, cmd)

    def stream_in_container(self, ns: str, name: str, container: str,
                            cmd: list[str], worker: int = 0,
                            tty: bool = False):
        """Interactive exec (kubectl exec -it): a Popen-like handle the
        kubelet API bridges over the WebSocket channel protocol."""
        if self.gang is None:
            raise NotImplementedError("no worker transport configured")
        try:
            qr = self._qr_for(ns, name)
        except (NotFoundError,) as e:
            raise KeyError(str(e)) from e
        return self.gang.stream_exec(qr, worker, cmd, tty=tty)

    # -- background loops (started by bootstrap; parity kubelet.go:374-376) ----

    def start(self):
        loops = [
            ("status", self.cfg.reconcile_interval_s, self.update_all_pod_statuses),
            ("notify", self.cfg.notify_interval_s, self.update_all_pod_statuses),
            ("pending", self.cfg.pending_retry_interval_s, self.process_pending_pods),
            ("cleanup", self.cfg.cleanup_interval_s, self.run_cleanup),
        ]
        for name, interval, fn in loops:
            t = threading.Thread(target=self._loop, args=(name, interval, fn),
                                 name=f"provider-{name}", daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2)

    def _loop(self, name: str, interval: float, fn: Callable[[], None]):
        while not self._stop.wait(interval):
            try:
                with self.metrics.time_block("tpu_kubelet_loop_seconds",
                                             {"loop": name}):
                    fn()
            except Exception as e:  # noqa: BLE001 — loops must survive anything
                log.exception("%s loop iteration failed: %s", name, e)
