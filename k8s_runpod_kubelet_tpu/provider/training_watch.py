"""Kubelet-side training-progress watch: scrape, annotate, stall detection.

The control-plane half of ISSUE 5. For every Running pod whose workload
emits the TPU_TELEMETRY line protocol (workloads/telemetry.py — train_main
prints one state line per step on worker-0), the reconcile loop:

- scrapes the NEWEST line out of worker-0's logs through the same
  ``GangExecutor`` log surface the preemption-recovery event already uses
  (so the fake-cloud path exercises the real parse),
- mirrors progress onto the pod as ``tpu.dev/goodput`` / ``tpu.dev/mfu`` /
  ``tpu.dev/last-step`` annotations (patched only on change),
- re-exports fleet-visible ``tpu_training_*`` gauges labeled by pod,
- flags a pod whose step counter stops advancing for ``cfg.stall_timeout_s``
  with a ``TrainingStalled`` Warning event + ``pod.training_stalled`` span
  (the degraded-signal vocabulary ISSUE 3 established), clearing the flag
  loudly when progress resumes.

Pods that never emit a telemetry line (serving, adopted workloads) get a
grace window of one stall timeout worth of per-sweep probes (first-step
compile can be long), then drop to one log-tail fetch per stall_timeout_s
— and are otherwise untouched.
"""

from __future__ import annotations

import json
import logging

from ..cloud.types import lookup_accelerator
from ..generations import cost_per_chip_hr, generation_of
from ..kube.client import KubeApiError
from ..workloads.telemetry import TELEMETRY_PATTERN
from .annotations import Annotations as A

log = logging.getLogger(__name__)


class TrainingWatchMixin:
    def _describe_training_metrics(self):
        m = self.metrics
        m.describe("tpu_training_pod_goodput",
                   "scraped per-pod goodput ratio (worker-0 telemetry)")
        m.describe("tpu_training_pod_mfu",
                   "scraped per-pod MFU (worker-0 telemetry)")
        m.describe("tpu_training_pod_tokens_per_second",
                   "scraped per-pod training throughput")
        m.describe("tpu_training_pod_last_step",
                   "scraped per-pod last completed optimizer step")
        m.describe("tpu_training_pod_stalled",
                   "1 while a training pod's step counter is not advancing")
        m.describe("tpu_kubelet_training_stalls",
                   "TrainingStalled events emitted (stall episodes seen)")

    def _scrape_training(self, key: str, pod: dict, info, detailed, now: float):
        """One telemetry pass for a Running training pod. Best-effort by
        construction: any transport/parse failure leaves the pod exactly as
        the last sweep did (the stall clock keeps running — a worker whose
        logs went dark IS not provably progressing)."""
        if self.gang is None or not info.workload_launched:
            return
        if not self._should_probe(info, now):
            return
        info.train_probe_at = now
        if info.train_first_probe_at is None:
            info.train_first_probe_at = now
        payload = None
        # elastic shrink can exclude worker 0: the renumbered process 0
        # (coordinator + telemetry aggregator) lives on the lowest SURVIVING
        # worker — scrape that VM's logs
        m = self.gang.last_in_logs(detailed.resource, TELEMETRY_PATTERN,
                                   worker_id=self.scrape_worker_id(info))
        if m is not None:
            try:
                payload = json.loads(m.group(1))
            except (json.JSONDecodeError, IndexError):
                payload = None
        if payload is not None and isinstance(payload.get("step"), int):
            self._note_training_progress(key, pod, info, payload, now)
        # the stall deadline applies from the FIRST telemetry sighting: a
        # pod that never reported is not known to be training at all
        if info.train_step_at is not None:
            self._check_training_stall(key, pod, info, now)

    def _should_probe(self, info, now: float) -> bool:
        """Known training pods (telemetry seen) probe every sweep. A pod
        that has never emitted a line gets a grace window of one stall
        timeout (first-step compile can be long), then drops to one probe
        per stall_timeout_s — serving pods must not pay a worker log fetch
        on every reconcile pass forever, but a late-blooming training pod
        is still picked up eventually."""
        if info.train_last_step is not None:
            return True
        if info.train_first_probe_at is None:
            return True
        if now - info.train_first_probe_at <= self.cfg.stall_timeout_s:
            return True
        return (info.train_probe_at is None
                or now - info.train_probe_at >= self.cfg.stall_timeout_s)

    def _note_training_progress(self, key: str, pod: dict, info,
                                payload: dict, now: float):
        step = int(payload["step"])
        goodput = float(payload.get("goodput") or 0.0)
        mfu = float(payload.get("mfu") or 0.0)
        tok_s = float(payload.get("tokens_per_sec") or 0.0)
        with self.lock:
            advanced = info.train_last_step is None or step > info.train_last_step
            if advanced:
                info.train_last_step = step
                info.train_step_at = now
            elif info.train_step_at is None:
                info.train_step_at = now
            was_stalled = info.train_stalled
            if advanced and was_stalled:
                info.train_stalled = False
        labels = {"pod": key}
        self.metrics.set_gauge("tpu_training_pod_goodput", goodput, labels)
        self.metrics.set_gauge("tpu_training_pod_mfu", mfu, labels)
        self.metrics.set_gauge("tpu_training_pod_tokens_per_second", tok_s,
                               labels)
        self.metrics.set_gauge("tpu_training_pod_last_step", float(step),
                               labels)
        if advanced and was_stalled:
            self.metrics.set_gauge("tpu_training_pod_stalled", 0.0, labels)
            log.info("pod %s training progress resumed at step %d", key, step)
            self.emit_event(pod, "TrainingProgressing",
                            f"step counter advancing again (step {step})")
        # fleet scheduler refinement (ISSUE 19): the SAME scrape teaches
        # the throughput matrix (measured MFU x roofline peak) and
        # refreshes the placement's preemption cost — unsaved work since
        # the last durable checkpoint (the ledger's telemetry field), so
        # a capacity crunch evicts the gang with the least to lose
        scheduler = getattr(self, "fleet_scheduler", None)
        if scheduler is not None:
            anns = pod.get("metadata", {}).get("annotations", {}) or {}
            unsaved = payload.get("unsaved_work_s")
            scheduler.observe_training(
                pod.get("metadata", {}).get("name", key),
                generation=anns.get(A.GENERATION, ""), mfu=mfu,
                goodput=goodput,
                unsaved_work_s=(float(unsaved)
                                if unsaved is not None else None))
        self._annotate_training(key, pod, info, step, goodput, mfu)

    def _annotate_training(self, key: str, pod: dict, info, step: int,
                           goodput: float, mfu: float):
        anns = {A.LAST_STEP: str(step), A.GOODPUT: f"{goodput:.3f}",
                A.MFU: f"{mfu:.3f}"}
        fingerprint = tuple(sorted(anns.items()))
        with self.lock:
            if fingerprint == info.train_annotated:
                return
        try:
            ns, name = key.split("/", 1)
            updated = self.kube.patch_pod(ns, name,
                                          {"metadata": {"annotations": anns}})
            with self.lock:
                info.train_annotated = fingerprint
                if key in self.pods:
                    self.pods[key] = updated
        except KubeApiError as e:
            log.debug("training annotate of %s failed (next sweep retries): %s",
                      key, e)

    def _check_training_stall(self, key: str, pod: dict, info, now: float):
        stalled_for = now - info.train_step_at
        if stalled_for <= self.cfg.stall_timeout_s:
            return
        with self.lock:
            if info.train_stalled:
                return  # one event/span per episode, not per sweep
            info.train_stalled = True
        self.metrics.set_gauge("tpu_training_pod_stalled", 1.0, {"pod": key})
        self.metrics.incr("tpu_kubelet_training_stalls")
        self.tracer.record("pod.training_stalled", info.train_step_at, now,
                           trace_id=info.trace_id, parent_id=info.trace_root,
                           attrs={"pod": key, "slice": info.qr_name,
                                  "last_step": info.train_last_step,
                                  "stalled_for_s": round(stalled_for, 3)})
        log.warning("pod %s training STALLED: step %s for %.0fs (> %.0fs)",
                    key, info.train_last_step, stalled_for,
                    self.cfg.stall_timeout_s)
        self.emit_event(pod, "TrainingStalled",
                        f"step counter stuck at {info.train_last_step} for "
                        f"{stalled_for:.0f}s (stall_timeout_s="
                        f"{self.cfg.stall_timeout_s:.0f})",
                        event_type="Warning")

    def _clear_training_gauges(self, key: str):
        """Drop the pod's labeled gauge series when it leaves (deleted,
        terminal, or requeued) — a phantom tpu_training_pod_stalled=1 for a
        pod that no longer exists would page someone forever. Unconditional
        (removal is idempotent): gating on train_last_step would leak the
        series of a pod whose requeue already reset that field."""
        labels = {"pod": key}
        for name in ("tpu_training_pod_goodput", "tpu_training_pod_mfu",
                     "tpu_training_pod_tokens_per_second",
                     "tpu_training_pod_last_step", "tpu_training_pod_stalled"):
            self.metrics.remove_gauge(name, labels)

    def training_status(self) -> dict:
        """/debug/train on the kubelet health server: the per-pod training
        telemetry the reconcile loop has scraped, joined with chip-second
        spend (ISSUE 20) so tools/cost_summary.py can report training and
        serving dollars side by side from one JSONL."""
        now = self.clock()
        with self.lock:
            pods = {}
            for key, info in self.instances.items():
                if info.train_last_step is None:
                    continue
                entry = {
                    "last_step": info.train_last_step,
                    "stalled": info.train_stalled,
                    "last_advance_age_s": round(
                        now - info.train_step_at, 3)
                    if info.train_step_at is not None else None,
                    "slice": info.qr_name,
                }
                # cost join: chips x elapsed-since-first-telemetry-probe,
                # priced off the ONE generations.py table (the scrape
                # epoch slightly undercounts provisioning time — the
                # slice's own binding annotations carry the full-lease
                # cost rate; this is the TRAINING-attributed share)
                acc = str(getattr(info, "accelerator_type", "") or "")
                first = getattr(info, "train_first_probe_at", None)
                if acc and first is not None:
                    gen = generation_of(acc)
                    spec = lookup_accelerator(acc)
                    chips = spec.chips if spec is not None else 0
                    chip_seconds = chips * max(0.0, now - first)
                    entry["generation"] = gen
                    entry["chips"] = chips
                    entry["chip_seconds"] = round(chip_seconds, 3)
                    entry["cost_dollars"] = round(
                        chip_seconds * cost_per_chip_hr(gen) / 3600.0, 6)
                pods[key] = entry
        return {"schema_version": 1, "pods": pods,
                "stall_timeout_s": self.cfg.stall_timeout_s}
