"""End-to-end demo: a live virtual TPU kubelet, driven like a user would.

Wires the full stack — fake Cloud TPU API (real HTTP server), TPU client,
node + pod controllers, provider with all background loops, kubelet API
server (real HTTP) — then plays the role of the K8s scheduler and a user:

  1. register the virtual node (capacity, taint, lease)
  2. "schedule" a MaxText-style pod requesting google.com/tpu: 16
  3. watch it go Pending -> gang launch on 4 workers -> Running
  4. curl the kubelet API for /pods and per-worker logs
  5. simulate a maintenance preemption -> observe gang-fail -> Failed
  6. delete the pod -> slice terminated

Run: python examples/demo_e2e.py
"""

import json
import sys
import time
import urllib.request

sys.path.insert(0, ".")

from k8s_runpod_kubelet_tpu.cloud import HttpTransport, TpuClient
from k8s_runpod_kubelet_tpu.cloud.fake_server import FakeTpuServer
from k8s_runpod_kubelet_tpu.config import Config
from k8s_runpod_kubelet_tpu.gang import GangExecutor, InMemoryWorkerTransport
from k8s_runpod_kubelet_tpu.kube import FakeKubeClient
from k8s_runpod_kubelet_tpu.kube import objects as ko
from k8s_runpod_kubelet_tpu.node import KubeletApiServer, NodeController, PodController
from k8s_runpod_kubelet_tpu.provider import Provider
from k8s_runpod_kubelet_tpu.provider.annotations import Annotations as A


def log(msg):
    print(f"[demo] {msg}", flush=True)


def wait_for(cond, timeout=15.0, what="condition"):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return time.time() - t0
        time.sleep(0.05)
    raise SystemExit(f"TIMEOUT waiting for {what}")


def main():
    # -- boot the kubelet ------------------------------------------------------
    server = FakeTpuServer(provision_delay_s=0.5).start()
    kube = FakeKubeClient()
    cfg = Config(node_name="virtual-tpu", zone="us-central2-b",
                 reconcile_interval_s=0.3, notify_interval_s=0.3,
                 pending_retry_interval_s=0.5, cleanup_interval_s=1.0)
    tpu = TpuClient(HttpTransport(server.base_url, token="demo"), "demo-proj",
                    cfg.zone)
    transport = InMemoryWorkerTransport()
    provider = Provider(cfg, kube, tpu, gang_executor=GangExecutor(transport))
    nc = NodeController(kube, provider, status_interval_s=1.0)
    pc = PodController(kube, provider, cfg.node_name, resync_interval_s=5.0)
    api = KubeletApiServer(provider, address="127.0.0.1", port=0)
    nc.start()
    pc.start()
    api.start()
    provider.start()
    provider.load_running()
    log(f"kubelet up; kubelet API on :{api.port}")

    node = kube.get_node("virtual-tpu")
    log(f"node registered: capacity google.com/tpu={node['status']['capacity']['google.com/tpu']}, "
        f"taint={node['spec']['taints'][0]['key']}={node['spec']['taints'][0]['value']}")
    lease = kube.get_lease("virtual-tpu")
    log(f"lease held by {lease['spec']['holderIdentity']}")

    # -- schedule a training pod ----------------------------------------------
    pod = {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "maxtext-llama3-8b", "namespace": "default",
                     "annotations": {A.GENERATION: "v5e"}},
        "spec": {"nodeName": "virtual-tpu", "restartPolicy": "Never",
                 "containers": [{
                     "name": "train", "image": "gcr.io/demo/maxtext:latest",
                     "resources": {"limits": {"google.com/tpu": "16"}},
                     "env": [{"name": "MODEL", "value": "llama3-8b"}]}]},
    }
    kube.create_pod(pod)
    log("pod maxtext-llama3-8b scheduled onto virtual-tpu (16 chips requested)")

    dt = wait_for(lambda: ko.annotations(kube.get_pod("default", "maxtext-llama3-8b"))
                  .get(A.QUEUED_RESOURCE), what="slice deploy")
    p = kube.get_pod("default", "maxtext-llama3-8b")
    ann = ko.annotations(p)
    log(f"deployed after {dt:.2f}s: slice={ann[A.QUEUED_RESOURCE]} "
        f"type={ann[A.ACCELERATOR_TYPE]} cost=${ann[A.COST_PER_HR]}/hr")

    dt = wait_for(lambda: ko.phase(kube.get_pod("default", "maxtext-llama3-8b")) == "Running",
                  what="pod Running")
    p = kube.get_pod("default", "maxtext-llama3-8b")
    log(f"pod RUNNING after {dt:.2f}s; podIP={p['status']['podIP']}")
    qr = server.service.get(ann[A.QUEUED_RESOURCE])
    log(f"gang: {len(qr.runtime)} workers launched; worker env sample: "
        f"TPU_WORKER_ID={qr.worker_env[2]['TPU_WORKER_ID']} "
        f"JAX_COORDINATOR_ADDRESS={qr.worker_env[2]['JAX_COORDINATOR_ADDRESS']} "
        f"TPU_TOPOLOGY={qr.worker_env[2]['TPU_TOPOLOGY']}")

    # -- kubelet API ----------------------------------------------------------
    base = f"http://127.0.0.1:{api.port}"
    pods = json.load(urllib.request.urlopen(f"{base}/pods"))
    log(f"GET /pods -> {len(pods['items'])} pod(s): "
        f"{[i['metadata']['name'] for i in pods['items']]}")
    for w in range(4):
        transport.append_log(qr.name, w, f"step 42 loss=2.17 worker={w}")
    logs = urllib.request.urlopen(
        f"{base}/containerLogs/default/maxtext-llama3-8b/train?worker=1").read().decode()
    log(f"GET /containerLogs?worker=1 -> {logs.strip()!r}")

    # -- preemption (the TPU-normal failure) ----------------------------------
    log("injecting maintenance preemption of worker 2 ...")
    server.service.preempt(qr.name, worker_id=2)
    wait_for(lambda: ko.phase(kube.get_pod("default", "maxtext-llama3-8b")) == "Failed",
             what="gang-fail")
    st = kube.get_pod("default", "maxtext-llama3-8b")["status"]
    log(f"pod FAILED: reason={st['reason']} msg={st['message'][:60]}...")

    # -- delete ---------------------------------------------------------------
    kube.delete_pod("default", "maxtext-llama3-8b")
    wait_for(lambda: kube.list_pods() == [], what="pod finalized")
    wait_for(lambda: server.service.resources == {}, what="slice terminated")
    log("pod deleted; slice terminated; cluster clean")

    # -- metrics --------------------------------------------------------------
    ready_lat = provider.metrics.get_observations("tpu_kubelet_schedule_to_ready_seconds")
    log(f"north-star metric (schedule->gang-running): {ready_lat[0]:.2f}s" if ready_lat
        else "no latency recorded")

    provider.stop()
    pc.stop()
    nc.stop()
    api.stop()
    server.stop()
    log("DEMO PASSED")


if __name__ == "__main__":
    main()
