# Multi-stage build mirroring the reference's shape (Dockerfile:1-22: builder ->
# distroless nonroot static binary). Python equivalent: deps layer -> slim
# runtime, non-root UID 65532, stdlib-only control plane (no pip installs needed
# for the kubelet itself; jax extras only for workload images).
FROM python:3.12-slim AS builder
WORKDIR /build
COPY k8s_runpod_kubelet_tpu/ k8s_runpod_kubelet_tpu/
COPY pyproject.toml .
RUN python -m compileall -q k8s_runpod_kubelet_tpu

# CI gate: graftlint (README "Static analysis") — the runtime stage copies
# the package FROM this stage, so an image cannot build with findings or
# stale allowlist entries. README + helm ride along because the
# config-plumbing and observability checkers lint the whole chain
# (config -> env -> flag -> helm template, metric/span -> catalogue).
FROM builder AS check
COPY README.md .
COPY helm/ helm/
RUN pip install --no-cache-dir "pyyaml>=6" \
    && python -m k8s_runpod_kubelet_tpu.analysis --format=github \
    && python -m compileall -q k8s_runpod_kubelet_tpu

FROM python:3.12-slim
LABEL org.opencontainers.image.source=https://github.com/tpu-virtual-kubelet/tpu-virtual-kubelet
WORKDIR /app
# pyyaml is the one required dep (pyproject.toml): --provider-config / kubeconfig parsing
RUN pip install --no-cache-dir "pyyaml>=6" && pip cache purge || true
COPY --from=check /build/k8s_runpod_kubelet_tpu/ k8s_runpod_kubelet_tpu/
# nonroot (parity: distroless nonroot uid 65532, Dockerfile:20)
RUN groupadd -g 65532 nonroot && useradd -u 65532 -g 65532 -m nonroot
USER 65532:65532
ENTRYPOINT ["python", "-m", "k8s_runpod_kubelet_tpu.cmd.main"]
