// Native training-data loader: mmap'd token files -> packed (B, S+1) batches.
//
// The IO half of the training input pipeline (the reference repo has no native
// components at all — SURVEY.md §2.1; this is part of the "exceeds" surface,
// filling the framework-runtime role a torch DataLoader's C++ workers play,
// TPU-shaped: fixed-size int32 batches ready for device_put, produced by
// background threads so host IO never sits on the device-step critical path).
//
// Data format: a raw little-endian int32 token stream (MaxText-style
// pre-tokenized corpus). Batches are windows of seq_len+1 tokens; window order
// is a seeded affine permutation over all windows, re-derived per epoch, so
// every worker process can compute its own disjoint shard deterministically
// (no coordination traffic — matches the SPMD "same program, own shard"
// model).
//
// Concurrency: N producer threads claim global batch indices with an atomic
// counter, build batches independently, and retire them through a bounded
// reorder buffer so the consumer sees batch 0, 1, 2, ... in order no matter
// which thread finished first. Determinism is therefore independent of thread
// count — a (seed, seq_len, batch, shard) tuple names the exact stream.
//
// extern "C" API (consumed by ctypes from
// k8s_runpod_kubelet_tpu/data/loader.py — keep in sync):
//   tl_open(path, seq_len, batch, seed, threads, capacity, vocab,
//           shard_id, num_shards, start_batch) -> handle (NULL on error;
//           path=="" => synthetic xorshift stream, the bench input path;
//           start_batch seeks the deterministic stream — checkpoint resume)
//   tl_next(handle, out_ptr) -> 0 (fills batch*(seq_len+1) int32s)
//   tl_num_tokens(handle) -> total tokens visible to this shard
//   tl_batches_per_epoch(handle)
//   tl_close(handle)

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

// splitmix64: seeds the per-sample xorshift streams; also the permutation hash.
static inline uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

struct Batch {
  std::vector<int32_t> data;
};

class Loader {
 public:
  Loader(const std::string& path, int64_t seq_len, int64_t batch,
         uint64_t seed, int threads, int capacity, int64_t vocab,
         int64_t shard_id, int64_t num_shards, uint64_t start_batch)
      : seq_len_(seq_len), batch_(batch), seed_(seed), vocab_(vocab),
        shard_id_(shard_id), num_shards_(num_shards),
        capacity_(capacity < 2 ? 2 : capacity),
        next_claim_(start_batch), next_consume_(start_batch) {
    if (!path.empty()) {
      fd_ = ::open(path.c_str(), O_RDONLY);
      if (fd_ < 0) { ok_ = false; return; }
      struct stat st;
      if (fstat(fd_, &st) != 0 || st.st_size < (seq_len_ + 1) * 4) {
        ok_ = false; return;
      }
      file_tokens_ = st.st_size / 4;
      map_ = static_cast<int32_t*>(
          mmap(nullptr, static_cast<size_t>(st.st_size), PROT_READ,
               MAP_PRIVATE, fd_, 0));
      if (map_ == MAP_FAILED) { map_ = nullptr; ok_ = false; return; }
      // windows stride by seq_len (the +1 target overlaps the next window's
      // first token — standard next-token-prediction packing)
      total_windows_ = (file_tokens_ - 1) / seq_len_;
    } else {
      // synthetic mode: "infinite" corpus
      total_windows_ = 1LL << 40;
    }
    if (num_shards_ > 1) {
      shard_windows_ = total_windows_ / num_shards_;
    } else {
      shard_windows_ = total_windows_;
    }
    if (shard_windows_ < batch_) { ok_ = false; return; }
    int n = threads < 1 ? 1 : (threads > 16 ? 16 : threads);
    for (int i = 0; i < n; ++i) {
      workers_.emplace_back([this] { Work(); });
    }
  }

  ~Loader() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_space_.notify_all();
    cv_ready_.notify_all();
    for (auto& t : workers_) t.join();
    if (map_) munmap(map_, static_cast<size_t>(file_tokens_ * 4));
    if (fd_ >= 0) ::close(fd_);
  }

  bool ok() const { return ok_; }
  int64_t num_tokens() const {
    return map_ ? shard_windows_ * seq_len_ : -1;
  }
  int64_t batches_per_epoch() const { return shard_windows_ / batch_; }

  // Blocking: copies the next in-order batch into out (batch*(seq_len+1)).
  int Next(int32_t* out) {
    std::unique_lock<std::mutex> lk(mu_);
    const uint64_t want = next_consume_++;
    cv_ready_.wait(lk, [&] { return stop_ || done_.count(want) > 0; });
    if (stop_) return -1;
    Batch b = std::move(done_[want]);
    done_.erase(want);
    lk.unlock();
    cv_space_.notify_all();
    std::memcpy(out, b.data.data(), b.data.size() * sizeof(int32_t));
    return 0;
  }

 private:
  // Seeded per-epoch permutation over the shard's windows, no materialized
  // index array even for billion-window corpora: an affine map with odd `a`
  // is a bijection mod the next power of two, and cycle-walking (re-applying
  // the map while the value lands in the pow2 overhang) restricts it to a
  // bijection on [0, shard_windows) — expected <2 steps since m < 2n.
  int64_t WindowFor(uint64_t global_sample) const {
    const uint64_t n = static_cast<uint64_t>(shard_windows_);
    uint64_t m = 1;
    while (m < n) m <<= 1;
    const uint64_t mask = m - 1;
    const uint64_t epoch = global_sample / n;
    const uint64_t i = global_sample % n;
    // shard_id mixed in so each SPMD shard gets an independent per-epoch
    // permutation (otherwise sample positions correlate across shards)
    const uint64_t sh = static_cast<uint64_t>(shard_id_) * 0x9e3779b97f4a7c15ULL;
    const uint64_t a = splitmix64(seed_ ^ (epoch * 2654435761ULL) ^ sh) | 1ULL;
    const uint64_t b = splitmix64(seed_ + epoch + 0x51ed270bULL + sh);
    uint64_t w = i;
    do {
      w = (a * w + b) & mask;
    } while (w >= n);
    return static_cast<int64_t>(w) + shard_id_ * shard_windows_;
  }

  void FillSample(uint64_t global_sample, int32_t* dst) const {
    const int64_t span = seq_len_ + 1;
    if (map_) {
      const int64_t w = WindowFor(global_sample);
      std::memcpy(dst, map_ + w * seq_len_,
                  static_cast<size_t>(span) * sizeof(int32_t));
    } else {
      uint64_t s = splitmix64(seed_ ^ (global_sample * 0x9e3779b9ULL)
                              ^ (static_cast<uint64_t>(shard_id_) << 48));
      for (int64_t t = 0; t < span; ++t) {
        s ^= s << 13; s ^= s >> 7; s ^= s << 17;  // xorshift64
        dst[t] = static_cast<int32_t>(s % static_cast<uint64_t>(vocab_));
      }
    }
  }

  void Work() {
    const int64_t span = seq_len_ + 1;
    for (;;) {
      uint64_t idx;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_space_.wait(lk, [&] {
          return stop_ || next_claim_ < next_consume_ + capacity_;
        });
        if (stop_) return;
        idx = next_claim_++;
      }
      Batch b;
      b.data.resize(static_cast<size_t>(batch_ * span));
      for (int64_t s = 0; s < batch_; ++s) {
        FillSample(idx * batch_ + s, b.data.data() + s * span);
      }
      {
        std::lock_guard<std::mutex> lk(mu_);
        done_[idx] = std::move(b);
      }
      cv_ready_.notify_all();
    }
  }

  const int64_t seq_len_, batch_;
  const uint64_t seed_;
  const int64_t vocab_, shard_id_, num_shards_;
  const uint64_t capacity_;

  int fd_ = -1;
  int32_t* map_ = nullptr;
  int64_t file_tokens_ = 0;
  int64_t total_windows_ = 0;
  int64_t shard_windows_ = 0;
  bool ok_ = true;

  std::mutex mu_;
  std::condition_variable cv_ready_, cv_space_;
  std::map<uint64_t, Batch> done_;   // reorder buffer, keyed by batch index
  uint64_t next_claim_;              // next batch index a worker builds
  uint64_t next_consume_;            // next batch index Next() hands out
  bool stop_ = false;
  std::vector<std::thread> workers_;
};

}  // namespace

extern "C" {

void* tl_open(const char* path, int64_t seq_len, int64_t batch, uint64_t seed,
              int32_t threads, int32_t capacity, int64_t vocab,
              int64_t shard_id, int64_t num_shards, uint64_t start_batch) {
  if (seq_len < 1 || batch < 1 || vocab < 2 || num_shards < 1 ||
      shard_id < 0 || shard_id >= num_shards) {
    return nullptr;
  }
  auto* l = new Loader(path ? std::string(path) : std::string(), seq_len,
                       batch, seed, threads, capacity, vocab, shard_id,
                       num_shards, start_batch);
  if (!l->ok()) { delete l; return nullptr; }
  return l;
}

int32_t tl_next(void* h, int32_t* out) {
  return h ? static_cast<Loader*>(h)->Next(out) : -1;
}

int64_t tl_num_tokens(void* h) {
  return h ? static_cast<Loader*>(h)->num_tokens() : -1;
}

int64_t tl_batches_per_epoch(void* h) {
  return h ? static_cast<Loader*>(h)->batches_per_epoch() : -1;
}

void tl_close(void* h) { delete static_cast<Loader*>(h); }

}  // extern "C"
