"""Benchmark: training throughput of the flagship Llama-architecture model.

Prints ONE JSON line:
  {"metric": "train_tokens_per_sec_per_chip", "value": N,
   "unit": "tok/s/chip", "vs_baseline": R, ...extras}

The reference publishes no performance numbers (BASELINE.md: "None exist"), so
vs_baseline is measured against the documented round-1 target in
_TARGET_TOK_S_PER_CHIP — a model-flops roofline estimate for the bench config
at 40% MFU on the detected chip generation. Beating 1.0 means beating that
roofline fraction.

Usage:
  python bench.py            # full run (TPU: real numbers; first compile ~30s)
  python bench.py --quick    # tiny config, CPU-friendly smoke (seconds)
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# bf16 peak TFLOP/s per chip by generation (public spec sheets)
_PEAK_TFLOPS = {"v4": 275.0, "v5e": 197.0, "v5p": 459.0, "v6e": 918.0,
                "cpu": 0.1}
_TARGET_MFU = 0.40


def detect_generation() -> str:
    import jax
    if jax.default_backend() != "tpu":
        return "cpu"
    kind = jax.devices()[0].device_kind.lower()
    for gen in ("v6e", "v5p", "v4"):
        if gen in kind:
            return gen
    if "v5" in kind:  # v5 lite
        return "v5e"
    return "v5e"


def main():
    quick = "--quick" in sys.argv
    import jax
    import jax.numpy as jnp
    from __graft_entry__ import _bench_config
    from k8s_runpod_kubelet_tpu.workloads.train import (TrainConfig, Trainer,
                                                        synthetic_batches)

    n_chips = jax.device_count()
    gen = detect_generation()
    cfg = _bench_config(tiny=quick)
    if quick:
        tc = TrainConfig(batch_size=2, seq_len=64, steps=3, warmup_steps=1)
        warmup_steps, timed_steps = 1, 2
    else:
        tc = TrainConfig(batch_size=8, seq_len=2048, steps=20, warmup_steps=1)
        warmup_steps, timed_steps = 3, 10

    mesh = None
    if n_chips > 1:
        from k8s_runpod_kubelet_tpu.parallel import MeshConfig, make_mesh
        mesh = make_mesh(MeshConfig())  # pure data-parallel over chips
        tc.batch_size *= n_chips

    trainer = Trainer(cfg, tc, mesh=mesh)
    batches = synthetic_batches(cfg, tc, mesh)

    trainer.run(steps=warmup_steps, batches=batches)  # compile + warm
    t0 = time.perf_counter()
    trainer.run(steps=timed_steps, batches=batches)
    wall = time.perf_counter() - t0

    tokens = tc.batch_size * tc.seq_len * timed_steps
    tok_s = tokens / wall
    tok_s_chip = tok_s / n_chips

    # model-flops roofline: 6*N flops per token (fwd+bwd)
    n_params = cfg.param_count
    mfu = (6.0 * n_params * tok_s_chip) / (_PEAK_TFLOPS[gen] * 1e12)
    target_tok_s_chip = _TARGET_MFU * _PEAK_TFLOPS[gen] * 1e12 / (6.0 * n_params)
    vs_baseline = tok_s_chip / target_tok_s_chip if target_tok_s_chip else 0.0

    print(json.dumps({
        "metric": "train_tokens_per_sec_per_chip",
        "value": round(tok_s_chip, 1),
        "unit": "tok/s/chip",
        "vs_baseline": round(vs_baseline, 3),
        "chips": n_chips,
        "generation": gen,
        "model": cfg.name,
        "params": n_params,
        "mfu": round(mfu, 3),
        "seq_len": tc.seq_len,
        "global_batch": tc.batch_size,
    }))


if __name__ == "__main__":
    main()
