"""Benchmark: training throughput of the flagship Llama-architecture model.

Prints ONE JSON line:
  {"metric": "train_tokens_per_sec_per_chip", "value": N,
   "unit": "tok/s/chip", "vs_baseline": R, ...extras}

The reference publishes no performance numbers (BASELINE.md: "None exist"), so
vs_baseline is measured against the documented target in _TARGET_MFU — a
model-flops roofline estimate for the bench config at 40% MFU on the detected
chip generation. Beating 1.0 means beating that roofline fraction.

Robustness (round-2 fix): the default invocation is an *orchestrator* that
imports no jax.  It runs the real bench in a child process (``--run``) with a
hard timeout, retries TPU-backend initialization (the axon TPU tunnel can be
slow or transiently unavailable), and falls back to a CPU smoke run if the TPU
never comes up — so this script always emits exactly one parseable JSON line,
never a bare traceback.

Usage:
  python bench.py            # full run (TPU: real numbers; first compile ~40s)
  python bench.py --quick    # tiny config, CPU-friendly smoke (seconds)
  python bench.py --run      # internal: run the bench in-process
  python bench.py --attn     # flash-attention microbench: Pallas vs XLA at
                             # S in {2k, 8k} + a 32k Pallas-only run (one
                             # JSON line per config; needs a TPU)
  python bench.py --serve    # serving bench: tokens/sec + p50/p99 latency
                             # under concurrent load (CPU-capable with the
                             # tiny model; real numbers on TPU)
  python bench.py --serve --model llama3-8b --int8 --kv-int8
                             # the BASELINE.md headline: tokens/sec/chip at
                             # 8B geometry on one v5e (int8 weights + int8
                             # KV fit the 16GB chip; zero-init weights —
                             # throughput is weight-value-independent)
  python bench.py --econ     # serving-economics A/B matrix: int8-KV,
                             # donation, speculation on/off (needs TPU)
  python bench.py --paged-attn  # paged-attention decode microbench: the
                             # page-table-gather kernel vs contiguous
                             # decode attention at the same geometry
                             # (CPU runs the reference path; the kernel
                             # claim needs a TPU)
  python bench.py --disagg   # disaggregated serving: KV handoff
                             # bytes/sec (serialize -> adopt across two
                             # paged arenas) + per-role TTFT/ITL through
                             # real engines (--smoke = codec cell only;
                             # CPU runs tiny geometry, claims need TPU)
  python bench.py --chunked  # chunked prefill + streamed handoff:
                             # serial-vs-streamed two-hop TTFT per
                             # prompt length (overlap efficiency) and
                             # co-resident ITL under a long prefill,
                             # chunked vs monolithic (--smoke = short
                             # sweep; CPU-capable, claims need TPU)
  python bench.py --handoff-path  # device-native vs wire KV handoff:
                             # page-run bytes/sec across two real arenas
                             # per path, and two-hop TTFT per path
                             # through real engines (--smoke = throughput
                             # cell only; CPU runs tiny geometry)
  python bench.py --kv-fabric  # fleet KV fabric (directory pulls): cold-
                             # replica TTFT with the prompt's KV pulled
                             # from its owner per rung (device/shm/wire
                             # through the real /kv_fetch ladder) vs the
                             # same replica re-prefilling cold (CPU runs
                             # tiny geometry, claims need TPU)
  python bench.py --flight-recorder  # serving flight recorder: recorder
                             # overhead on identical seeded traffic
                             # (median step wall, enabled vs disabled) +
                             # step-phase p50s and the watchdog's
                             # recompile count from the enabled arm
                             # (CPU-capable; chip phases need TPU)
  python bench.py --cost     # cost attribution meter: attributed
                             # chip-seconds vs externally timed request
                             # walls x chips (telescope identity), meter
                             # on/off step-wall overhead, and idle burn
                             # on a saturated arm (CPU-capable; the
                             # $/Mtok headline needs TPU list prices to
                             # mean anything)
  python bench.py --mfu-sweep  # training MFU levers: remat none/dots,
                             # batch, 530M width (needs TPU)
  python bench.py --attn-tune  # flash block-size grid at the training
                             # geometry S=2048/hd=64 (needs TPU)
  python bench.py --mla      # MLA absorbed decode vs like-for-like QKVO
                             # block, wall-clock (needs TPU)
  python bench.py --watch    # session watcher: probe on an interval, run
                             # the whole staged runbook on first success
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)

# bf16 peak TFLOP/s per chip by generation — ONE table shared with the live
# telemetry layer and the fleet scheduler (k8s_runpod_kubelet_tpu/
# generations.py, ISSUE 19), so the bench's offline MFU, a running worker's
# tpu_training_mfu_ratio gauge and the scheduler's goodput-per-dollar math
# all use the same roofline. No dict-literal fallback: test_generations.py
# pins this module as the single source of truth.
from k8s_runpod_kubelet_tpu.generations import (
    GENERATIONS as _GENERATIONS, PEAK_TFLOPS_BF16 as _PEAK_TFLOPS)
_TARGET_MFU = 0.40

_TPU_ATTEMPTS = 3          # orchestrator: tries at the TPU backend
_TPU_TIMEOUT_S = 1500      # per attempt: first compile can take minutes
_TPU_RETRY_SLEEP_S = 20
_PROBE_TIMEOUT_S = 300     # one cheap backend-init probe before the attempt
                           # loop: a WEDGED tunnel hangs (not errors), and
                           # burning the full attempt timeout x3 on hangs
                           # could outlast the driver's own deadline. 300s is
                           # deliberately generous — a slow-but-alive tunnel
                           # must not be misread as dead.
_CPU_TIMEOUT_S = 600

# Session watcher (r3 VERDICT item 1b: a one-shot probe at driver time can
# miss every usable window of a flapping tunnel). ``bench.py --watch`` probes
# on an interval for a whole build session; the moment the chip answers it
# runs the full staged runbook and persists every JSON line under
# _RESULTS_DIR. The driver-time orchestrator then prefers a live TPU run but
# falls back to the freshest persisted TPU result before falling back to CPU.
_RESULTS_DIR = os.path.join(_HERE, "bench_results")
_WATCH_INTERVAL_S = 600
_WATCH_BUDGET_S = 8 * 3600
_STEP_MAX_ATTEMPTS = 3     # consecutive failures with a HEALTHY tunnel
_SESSION_MAX_AGE_S = float(os.environ.get("BENCH_SESSION_MAX_AGE_S",
                                          str(24 * 3600)))

# The staged runbook (ROUND3_NOTES.md order): name, child argv, per-step
# timeout. Each step is a separate process so an OOM/hang is contained.
_STAGED_QUEUE = [
    ("headline", ["--run", "--expect-tpu"], 1800),
    ("mfu_sweep", ["--mfu-sweep"], 3600),
    ("attn_tune", ["--attn-tune"], 2400),
    # paged-attention decode (ISSUE 8): the serving engine's prefix-pool
    # layout driven through the Pallas kernel vs contiguous decode
    ("paged_attn", ["--paged-attn"], 1800),
    # disaggregated serving (ISSUE 9): KV handoff bytes/sec at the 8B KV
    # geometry + per-role TTFT/ITL (prefill hop, decode-with-adopted-KV,
    # unified cold) through real engines on the paged decode loop
    ("disagg", ["--disagg"], 2400),
    # chunked prefill + streamed handoff (ISSUE 10): serial-vs-streamed
    # two-hop TTFT sweep + ITL-under-long-prefill, chunked vs monolithic
    ("chunked", ["--chunked"], 2400),
    # device-native KV handoff (ISSUE 11): device vs wire page-run
    # throughput + two-hop TTFT per path on the same arena geometry
    ("handoff_path", ["--handoff-path"], 2400),
    # fleet KV fabric (ISSUE 16): directory-pull TTFT per rung through
    # the real /kv_fetch ladder vs cold re-prefill on the same replica
    ("kv_fabric", ["--kv-fabric"], 2400),
    # serving flight recorder (ISSUE 17): recorder overhead on identical
    # seeded traffic + the step-phase/recompile numbers it surfaces
    ("flight_recorder", ["--flight-recorder"], 2400),
    # heterogeneous fleet scheduler (ISSUE 19): hetero goodput-per-dollar
    # placement vs round-robin on identical seeded traffic over a fake
    # cloud of mixed generations — pure control plane, no chip needed
    ("scheduler", ["--scheduler"], 900),
    # cost attribution meter (ISSUE 20): telescope identity + on/off
    # overhead + saturated-arm idle burn through real engines, and the
    # $/Mtok headline priced off generations.py when the chip answers
    ("cost", ["--cost"], 2400),
    ("serve_8b", ["--serve", "--model", "llama3-8b", "--int8", "--kv-int8"],
     2400),
    # int4 weights via the Pallas unpack kernel (ops/int4_matmul.py):
    # weight HBM halves again vs int8 — the chip decides what that buys
    ("serve_8b_int4",
     ["--serve", "--model", "llama3-8b", "--int4", "--kv-int8"], 2400),
    ("econ", ["--econ"], 2400),
    # MLA latent-cache serving at the 8B weight class: the architecture
    # A/B against serve_8b (same class; int8 cache 18.4KB/token over 32
    # layers vs llama3-8b's 64KB K+V — 3.5x fewer cache bytes)
    ("serve_mla_8b",
     ["--serve", "--model", "mla-8b", "--int8", "--kv-int8"], 2400),
    ("ring_flash", ["--ring-flash"], 1800),
    ("spec_drift", ["--spec-drift"], 2400),
    # VERDICT r3 item 2: if the sweep tops out short of 0.40 MFU, the claim
    # needs a profile, not a guess — capture an XLA trace of the headline's
    # timed steps whenever the chip answers (TensorBoard-readable xplane)
    ("headline_profile",
     ["--run", "--expect-tpu", "--profile-dir",
      os.path.join("bench_results", "tpu_profile")], 1800),
    ("mla", ["--mla"], 1200),    # latent-attention op vs QKVO block
    ("attn", ["--attn"], 2400),  # 32k last inside; sacrificial process
]


# --------------------------------------------------------------------------
# child: the actual benchmark, run in-process
# --------------------------------------------------------------------------

def _force_platform_from_env() -> None:
    """Honor JAX_PLATFORMS=cpu even on images (axon) whose sitecustomize
    registers a TPU platform before env vars are read."""
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        import jax
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass  # backend already initialized; use what we have


def detect_generation() -> str:
    import jax
    if jax.default_backend() != "tpu":
        return "cpu"
    kind = jax.devices()[0].device_kind.lower()
    for gen in ("v6e", "v5p", "v4"):
        if gen in kind:
            return gen
    return "v5e"  # v5 lite and unknown-v5 default


def _emit(obj: dict) -> None:
    print(json.dumps(obj), flush=True)


def run_bench(quick: bool, expect_tpu: bool = False) -> dict:
    _force_platform_from_env()
    import jax

    # Fail fast (with a parseable error) instead of a traceback if the
    # backend cannot initialize — the orchestrator retries / falls back.
    try:
        n_chips = jax.device_count()
        backend = jax.default_backend()
    except Exception as e:  # noqa: BLE001 - any backend-init failure
        return {"metric": "train_tokens_per_sec_per_chip", "value": None,
                "unit": "tok/s/chip", "vs_baseline": None,
                "error": f"backend-init: {type(e).__name__}: {e}"[:500]}
    if expect_tpu and backend != "tpu":
        # jax silently fell back to CPU — don't burn an hour running the
        # full config there; let the orchestrator take the quick CPU path.
        return {"metric": "train_tokens_per_sec_per_chip", "value": None,
                "unit": "tok/s/chip", "vs_baseline": None,
                "error": f"expected tpu backend, got {backend!r}"}

    from __graft_entry__ import _bench_config
    from k8s_runpod_kubelet_tpu.workloads.train import (TrainConfig, Trainer,
                                                        synthetic_batches)

    gen = detect_generation()
    cfg = _bench_config(tiny=quick)
    if quick:
        tc = TrainConfig(batch_size=2, seq_len=64, steps=3, warmup_steps=1)
        warmup_steps, timed_steps = 1, 2
    else:
        tc = TrainConfig(batch_size=8, seq_len=2048, steps=20, warmup_steps=1)
        warmup_steps, timed_steps = 3, 10

    mesh = None
    if n_chips > 1:
        from k8s_runpod_kubelet_tpu.parallel import MeshConfig, make_mesh
        mesh = make_mesh(MeshConfig())  # pure data-parallel over chips
        tc.batch_size *= n_chips

    # goodput ledger on the timed run (ISSUE 5): the headline row records
    # where wall time went (productive vs compile/checkpoint/stall), so
    # BENCH_rXX.json carries goodput next to MFU and the perf trajectory is
    # self-reporting. Attached for the warmup too — warmup compile lands in
    # the compile bucket, exactly what a goodput report should show.
    try:
        from k8s_runpod_kubelet_tpu.workloads.telemetry import (
            TrainingTelemetry)
        tel = TrainingTelemetry(tokens_per_step=tc.batch_size * tc.seq_len,
                                model_params=cfg.param_count, n_chips=n_chips,
                                accelerator_type=gen)
    except Exception:  # noqa: BLE001 — same contract as the peak-table
        tel = None     # fallback: the number still lands, minus goodput
    trainer = Trainer(cfg, tc, mesh=mesh, telemetry=tel)
    batches = synthetic_batches(cfg, tc, mesh)

    trainer.run(steps=warmup_steps, batches=batches)  # compile + warm
    profile_dir = _arg_value("--profile-dir", "")
    if profile_dir:  # trace ONLY timed steps (VERDICT r2: profile, don't guess)
        trace_started_at = time.time()   # wall clock: gates capture mtime
        jax.profiler.start_trace(profile_dir)
    t0 = time.perf_counter()
    trainer.run(steps=timed_steps, batches=batches)
    wall = time.perf_counter() - t0
    if profile_dir:
        jax.profiler.stop_trace()
        # emit the bottleneck table alongside the number: top device-plane
        # ops from THIS run's capture (mtime-gated on the timed window so a
        # stale pb from a previous round is never misattributed)
        try:
            from tools.xplane_summary import newest_xplane, summarize
            pb = newest_xplane(profile_dir, since=trace_started_at)
            if pb is None:
                _emit({"metric": "profile_top_ops", "value": None,
                       "error": f"no fresh xplane.pb under {profile_dir}"})
            else:
                for plane in summarize(pb, top=6):
                    name = plane["plane"]
                    if "TPU" not in name and "host" not in name:
                        continue
                    _emit({"metric": "profile_top_ops", "plane": name,
                           "busy_ms": round(plane["busy_ms"], 2),
                           "top": [[nm[:80], round(ms, 3), c,
                                    round(share, 3)]
                                   for nm, ms, c, share in plane["top"]]})
        except Exception as e:  # noqa: BLE001 — the number must still land
            _emit({"metric": "profile_top_ops", "value": None,
                   "error": f"{type(e).__name__}: {e}"[:200]})

    tokens = tc.batch_size * tc.seq_len * timed_steps
    tok_s = tokens / wall
    tok_s_chip = tok_s / n_chips

    # model-flops roofline: 6*N flops per token (fwd+bwd)
    n_params = cfg.param_count
    mfu = (6.0 * n_params * tok_s_chip) / (_PEAK_TFLOPS[gen] * 1e12)
    target_tok_s_chip = _TARGET_MFU * _PEAK_TFLOPS[gen] * 1e12 / (6.0 * n_params)
    vs_baseline = tok_s_chip / target_tok_s_chip if target_tok_s_chip else 0.0

    return {
        "metric": "train_tokens_per_sec_per_chip",
        "value": round(tok_s_chip, 1),
        "unit": "tok/s/chip",
        "vs_baseline": round(vs_baseline, 3),
        "chips": n_chips,
        "generation": gen,
        "model": cfg.name,
        "params": n_params,
        "mfu": round(mfu, 3),
        "goodput": round(tel.ledger.goodput, 3) if tel else None,
        "goodput_buckets": {k: round(v, 3) for k, v in
                            tel.ledger.snapshot()["buckets"].items()
                            if v > 0} if tel else None,
        "seq_len": tc.seq_len,
        "global_batch": tc.batch_size,
    }


def run_attn_bench() -> int:
    """Flash-attention microbench (VERDICT r1 item 4): Pallas vs XLA,
    fwd+bwd, llama3-8b head geometry (Hq=32, Hkv=8, D=128), bf16.
    The XLA path materializes the (S, S) scores so it is only feasible at
    2k/8k; 32k runs Pallas-only to prove the streamed K/V fits VMEM."""
    _force_platform_from_env()
    import jax
    import jax.numpy as jnp
    from k8s_runpod_kubelet_tpu.ops.attention import (_attention_xla,
                                                      flash_attention,
                                                      tuned_block_sizes)

    if jax.default_backend() != "tpu":
        _emit({"metric": "flash_attn_speedup", "value": None,
               "error": f"attn bench needs a TPU, got {jax.default_backend()!r}"})
        return 1

    b, hq, hkv, d = 1, 32, 8, 128
    key = jax.random.PRNGKey(0)

    def time_fn(f, *args, iters=20):
        f(*args)[0].block_until_ready()  # compile + warm
        t0 = time.perf_counter()
        for _ in range(iters):
            out = f(*args)
        jax.tree_util.tree_leaves(out)[0].block_until_ready()
        return (time.perf_counter() - t0) / iters

    for s, with_xla in ((2048, True), (8192, True), (32768, False)):
        ks = jax.random.split(key, 4)
        q = jax.random.normal(ks[0], (b, hq, s, d), jnp.bfloat16)
        k = jax.random.normal(ks[1], (b, hkv, s, d), jnp.bfloat16)
        v = jax.random.normal(ks[2], (b, hkv, s, d), jnp.bfloat16)
        g = jax.random.normal(ks[3], (b, hq, s, d), jnp.bfloat16)

        def vjp_of(fn):
            def run(q, k, v):
                out, pull = jax.vjp(fn, q, k, v)
                return pull(g)
            return jax.jit(run)

        pallas_fn = vjp_of(lambda q, k, v: flash_attention(
            q, k, v, causal=True, use_pallas=True))
        t_pallas = time_fn(pallas_fn, q, k, v)
        if s >= 8192:
            # Mistral geometry: W=4096 sliding window — the kernels skip
            # blocks outside the band, so windowed time should approach
            # W/S of full-causal as S grows (the O(S*W) claim, measured)
            win_fn = vjp_of(lambda q, k, v: flash_attention(
                q, k, v, causal=True, use_pallas=True, sliding_window=4096))
            t_win = time_fn(win_fn, q, k, v)
            _emit({"metric": f"flash_attn_sw4096_s{s}", "unit": "ms",
                   "value": round(t_win * 1e3, 3),
                   "full_causal_ms": round(t_pallas * 1e3, 3),
                   "speedup_vs_full": round(t_pallas / t_win, 2)})
        # causal fwd+bwd model flops: fwd 2 matmuls, bwd 5 -> 3.5x fwd pair
        flops = 3.5 * 2 * b * hq * s * s * d  # causal halves via /2 below
        rec = {"metric": f"flash_attn_s{s}", "unit": "ms",
               "value": round(t_pallas * 1e3, 3),
               "tflops": round(flops / 2 / t_pallas / 1e12, 1),
               "blocks": tuned_block_sizes(s, s)}
        if with_xla:
            # the XLA path materializes (S, S) f32 scores (plus the vjp
            # residual); past ~4k that OOMs HBM — report pallas-only then
            try:
                xla_fn = vjp_of(lambda q, k, v: _attention_xla(
                    q, k, v, causal=True, sm_scale=d ** -0.5))
                t_xla = time_fn(xla_fn, q, k, v)
                rec["xla_ms"] = round(t_xla * 1e3, 3)
                rec["speedup_vs_xla"] = round(t_xla / t_pallas, 2)
            except Exception as e:  # noqa: BLE001 - typically RESOURCE_EXHAUSTED
                rec["xla_ms"] = None
                rec["xla_error"] = f"{type(e).__name__}: {e}"[:120]
        _emit(rec)
    return 0


def run_paged_attn_bench(smoke: bool = False) -> int:
    """Paged-attention decode microbench (ISSUE 8): the page-table-gather
    kernel over the serving engine's paged prefix-pool layout vs
    contiguous decode attention at the same geometry (llama3-8b heads on
    TPU). One JSON line per sequence length, carrying kv_page_bytes (per
    layer, K+V) so the row ties back to the pool-sizing knobs. CPU runs
    the pure-jnp reference path — a shape/ratio smoke, not a kernel
    claim; the watcher queues this step for the chip.

    ISSUE 12 adds the TP cell (_paged_tp_cell): per-chip decode step
    time for paged vs contiguous MESH engines at tp=2 (and tp=4 on a
    big-enough chip count) — the number the eligibility-gate lift is
    for."""
    # the TP cell needs >= 2 devices: on a CPU run, split the host into
    # virtual devices BEFORE jax initializes (harmless for the microbench)
    if os.environ.get("JAX_PLATFORMS", "") == "cpu" \
            and "xla_force_host_platform_device_count" \
            not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=2").strip()
    _force_platform_from_env()
    import jax
    import jax.numpy as jnp
    from k8s_runpod_kubelet_tpu.ops.attention import (_attention_xla,
                                                      paged_attention)

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        b, hq, hkv, d, t = 8, 32, 8, 128, 64
        dtype, seqs, iters = jnp.bfloat16, (2048, 8192), 50
    else:
        b, hq, hkv, d, t = 4, 8, 2, 128, 8
        dtype, seqs, iters = jnp.float32, (256,), 10
    key = jax.random.PRNGKey(0)

    def timed(f, iters=iters):
        f().block_until_ready()  # compile + warm
        t0 = time.perf_counter()
        for _ in range(iters):
            out = f()
        out.block_until_ready()
        return (time.perf_counter() - t0) / iters

    for s in seqs:
        n = s // t
        n_pages = n * b
        ks = jax.random.split(key, 3)
        q = jax.random.normal(ks[0], (b, hq, d), dtype)
        k_pages = jax.random.normal(ks[1], (n_pages, t, hkv, d), dtype)
        v_pages = jax.random.normal(ks[2], (n_pages, t, hkv, d), dtype)
        # a shuffled table: the kernel must win THROUGH the indirection,
        # not because pages happen to be laid out contiguously
        import numpy as _np
        pt = jnp.asarray(_np.random.default_rng(0).permutation(n_pages)
                         .reshape(b, n), jnp.int32)
        lengths = jnp.full((b,), s, jnp.int32)
        paged_s = timed(lambda: paged_attention(
            q, k_pages, v_pages, pt, lengths, use_pallas=on_tpu))
        # contiguous baseline: same data pre-gathered to (B, Hkv, S, D),
        # causal decode attention at the last position
        kc = k_pages[pt].reshape(b, s, hkv, d).transpose(0, 2, 1, 3)
        vc = v_pages[pt].reshape(b, s, hkv, d).transpose(0, 2, 1, 3)
        qc = q[:, :, None, :]
        contig = jax.jit(lambda qq, kk, vv, _s=s: _attention_xla(
            qq, kk, vv, causal=True, sm_scale=d ** -0.5, q_offset=_s - 1))
        contig_s = timed(lambda: contig(qc, kc, vc))
        _emit({"metric": "paged_attn_decode_us",
               "value": round(paged_s * 1e6, 1), "unit": "us/step",
               "contiguous_us": round(contig_s * 1e6, 1),
               "paged_over_contiguous": round(paged_s / contig_s, 3),
               "seq_len": s, "page_tokens": t,
               "kv_page_bytes": 2 * t * hkv * d * dtype(0).nbytes,
               "batch": b, "q_heads": hq, "kv_heads": hkv, "head_dim": d,
               "pallas": bool(on_tpu),
               "dtype": dtype.__name__,
               "backend": jax.default_backend()})
    _paged_tp_cell(smoke)
    _paged_prefill_cell(smoke)
    _paged_spec_cell(smoke)
    return 0


def _paged_prefill_cell(smoke: bool) -> None:
    """Prefill-into-arena TTFT cell (ISSUE 14): end-to-end submit->first-
    token latency through REAL engines, paged-NATIVE prefill (chunks
    scatter K/V straight into the arena pages) vs the dense-scratch
    route (prefill into a contiguous scratch cache, then fill_pages-copy
    into the pool) — the copy the native path deletes. Distinct prompts
    per iteration so the prefix cache never shortcuts the measured span.
    CPU numbers are an overhead smoke (explicitly backend=cpu); the chip
    claim waits on the tunnel."""
    import statistics
    import numpy as _np
    import jax
    import jax.numpy as jnp
    from k8s_runpod_kubelet_tpu.models import init_params, tiny_llama
    from k8s_runpod_kubelet_tpu.workloads.serving import (ServingConfig,
                                                          ServingEngine)

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = _serve_model("llama3-8b")
        shapes = jax.eval_shape(
            lambda: init_params(cfg, jax.random.PRNGKey(0)))
        params = jax.tree_util.tree_map(
            lambda sd: _np.zeros(sd.shape, sd.dtype), shapes)
        prompt_len, int8 = 1024, True
        sc_kw = dict(slots=4, cache_len=2048, max_prefill_len=1024,
                     kv_page_tokens=16, quantize_int8=True)
        iters = 3 if smoke else 10
    else:
        cfg = tiny_llama(vocab_size=128, embed_dim=64, n_layers=2,
                         n_heads=4, n_kv_heads=2, mlp_dim=128,
                         max_seq_len=512, dtype=jnp.float32,
                         param_dtype=jnp.float32)
        params = init_params(cfg, jax.random.PRNGKey(0))
        prompt_len, int8 = 96, False
        sc_kw = dict(slots=2, cache_len=256, max_prefill_len=128,
                     kv_page_tokens=8)
        iters = 3 if smoke else 8

    native = ServingEngine(cfg, params, ServingConfig(**sc_kw)).start()
    dense = ServingEngine(cfg, params, ServingConfig(
        **sc_kw, paged_prefill=False)).start()
    try:
        assert native._paged_prefill_on and not dense._paged_prefill_on
        rng = _np.random.default_rng(0)

        def prompts(n):
            return [[int(x) for x in rng.integers(
                1, cfg.vocab_size - 8, prompt_len)] for _ in range(n)]

        def ttft_ms(e):
            for p in prompts(2):  # compile + warm outside the cohort
                e.submit(p, max_new_tokens=1).result(timeout=600)
            samples = []
            for p in prompts(iters):
                t0 = time.perf_counter()
                e.submit(p, max_new_tokens=1).result(timeout=600)
                samples.append((time.perf_counter() - t0) * 1e3)
            return statistics.median(samples)

        native_ms = ttft_ms(native)
        dense_ms = ttft_ms(dense)
        assert native.metrics.get_counter(
            "tpu_serving_paged_prefill_tokens") > 0
        _emit({"metric": "paged_prefill_ttft_ms",
               "value": round(native_ms, 2), "unit": "ms",
               "dense_fill_ttft_ms": round(dense_ms, 2),
               "native_over_dense": round(native_ms / dense_ms, 3),
               "prompt_tokens": prompt_len,
               "page_tokens": sc_kw["kv_page_tokens"], "int8": int8,
               "iters": iters, "model": cfg.name,
               "backend": jax.default_backend()})
    finally:
        native.stop()
        dense.stop()


def _paged_spec_cell(smoke: bool) -> None:
    """Speculative-decode throughput cell (ISSUE 14): generated tokens/s
    through REAL engines with speculate_k drafts, the paged loop (multi-
    token verify over per-slot page tables, page-native rollback) vs the
    contiguous speculative loop. Greedy repetitive traffic so the bigram
    proposer lands accepts on both sides; acceptance counters ride the
    row so a throughput delta can be read against draft quality. CPU is
    an overhead smoke (backend=cpu); the chip claim waits on the
    tunnel."""
    import numpy as _np
    import jax
    import jax.numpy as jnp
    from k8s_runpod_kubelet_tpu.models import init_params, tiny_llama
    from k8s_runpod_kubelet_tpu.workloads.serving import (ServingConfig,
                                                          ServingEngine)

    on_tpu = jax.default_backend() == "tpu"
    k = 3
    if on_tpu:
        cfg = _serve_model("llama3-8b")
        shapes = jax.eval_shape(
            lambda: init_params(cfg, jax.random.PRNGKey(0)))
        params = jax.tree_util.tree_map(
            lambda sd: _np.zeros(sd.shape, sd.dtype), shapes)
        sc_kw = dict(slots=4, cache_len=2048, max_prefill_len=256,
                     kv_page_tokens=16, quantize_int8=True,
                     max_new_tokens=256, speculate_k=k)
        new_toks = 128 if smoke else 256
    else:
        cfg = tiny_llama(vocab_size=128, embed_dim=64, n_layers=2,
                         n_heads=4, n_kv_heads=2, mlp_dim=128,
                         max_seq_len=512, dtype=jnp.float32,
                         param_dtype=jnp.float32)
        params = init_params(cfg, jax.random.PRNGKey(0))
        sc_kw = dict(slots=2, cache_len=256, max_prefill_len=32,
                     kv_page_tokens=8, max_new_tokens=128, speculate_k=k)
        new_toks = 48 if smoke else 96
    prompt = [5, 6, 7] * 4

    paged = ServingEngine(cfg, params, ServingConfig(**sc_kw)).start()
    contig = ServingEngine(cfg, params, ServingConfig(
        **sc_kw, paged_decode=False)).start()
    try:
        assert paged._paged_loop and paged._paged_verify is not None

        def tok_s(e):
            # full-length warm run: a short warm leaves the longer run's
            # compile buckets (eviction, slot-finish shapes) cold and the
            # measured span would compare compiles, not decode
            e.submit(prompt, max_new_tokens=new_toks).result(timeout=600)
            t0 = time.perf_counter()
            out = e.submit(prompt, max_new_tokens=new_toks).result(
                timeout=600)
            return len(out["tokens"]) / (time.perf_counter() - t0)

        paged_tok_s = tok_s(paged)
        contig_tok_s = tok_s(contig)
        prop = paged.metrics.get_counter("tpu_serving_spec_proposed")
        acc = paged.metrics.get_counter("tpu_serving_spec_accepted")
        _emit({"metric": "paged_spec_decode_tok_s",
               "value": round(paged_tok_s, 1), "unit": "tok/s",
               "contiguous_tok_s": round(contig_tok_s, 1),
               "paged_over_contiguous": round(
                   paged_tok_s / contig_tok_s, 3),
               "speculate_k": k, "new_tokens": new_toks,
               "spec_acceptance": round(acc / prop, 3) if prop else None,
               "paged_spec_steps": paged.metrics.get_counter(
                   "tpu_serving_paged_speculative_steps"),
               "rollback_pages": paged.metrics.get_counter(
                   "tpu_serving_paged_speculative_rollback_pages"),
               "model": cfg.name,
               "backend": jax.default_backend()})
    finally:
        paged.stop()
        contig.stop()


def _paged_tp_cell(smoke: bool) -> None:
    """Tensor-parallel paged serving cell (ISSUE 12): per-chip decode
    step time through REAL mesh engines, paged vs contiguous, per tp
    degree. Both engines are built over the SAME mesh and measured at
    full slot occupancy on identical shapes — the paged step runs the
    shard_mapped page-table kernels over the sharded arena, the
    contiguous step the mesh decode the gate used to force. The win
    paged serving buys is memory/zero-copy (no per-slot contiguous
    cache, zero-copy prefix/handoff reuse); this cell pins that the hot
    step itself holds >= parity. CPU runs tp=2 over virtual devices
    with the tiny model — an overhead smoke, explicitly backend=cpu;
    the chip claim (llama3-8b int8 at tp=2/tp=4) waits on the tunnel."""
    import numpy as _np
    import jax
    import jax.numpy as jnp
    from k8s_runpod_kubelet_tpu.models import tiny_llama
    from k8s_runpod_kubelet_tpu.parallel import MeshConfig, make_mesh
    from k8s_runpod_kubelet_tpu.workloads.serving import (ServingConfig,
                                                          ServingEngine)

    on_tpu = jax.default_backend() == "tpu"
    n_dev = len(jax.devices())
    degrees = [d for d in ((2, 4) if on_tpu else (2,)) if d <= n_dev]
    if not degrees:
        _emit({"metric": "paged_tp_decode_step_us", "value": None,
               "unit": "us/step", "error": f"needs >= 2 devices, jax "
               f"sees {n_dev}", "backend": jax.default_backend()})
        return
    if on_tpu:
        from k8s_runpod_kubelet_tpu.models import init_params
        cfg = _serve_model("llama3-8b")
        # HOST zeros: the engine quantizes to int8 and device_puts the
        # sharded tree (serve_main --int8 strategy; bf16 8B never sits
        # whole in HBM)
        shapes = jax.eval_shape(
            lambda: init_params(cfg, jax.random.PRNGKey(0)))
        params = jax.tree_util.tree_map(
            lambda sd: _np.zeros(sd.shape, sd.dtype), shapes)
        slots, cache_len, page_tokens, int8 = 8, 2048, 16, True
        iters = 10 if smoke else 50
    else:
        from k8s_runpod_kubelet_tpu.models import init_params
        cfg = tiny_llama(vocab_size=128, embed_dim=64, n_layers=2,
                         n_heads=4, n_kv_heads=2, mlp_dim=128,
                         max_seq_len=256, dtype=jnp.float32,
                         param_dtype=jnp.float32)
        params = None  # init per mesh below
        slots, cache_len, page_tokens, int8 = 2, 64, 4, False
        iters = 5 if smoke else 20

    for tp in degrees:
        mesh = make_mesh(MeshConfig(data=1, tensor=tp), jax.devices()[:tp])
        mesh_params = (params if on_tpu
                       else init_params(cfg, jax.random.PRNGKey(0), mesh))
        sc_kw = dict(slots=slots, cache_len=cache_len,
                     max_prefill_len=cache_len // 2,
                     kv_page_tokens=page_tokens, quantize_int8=int8)
        paged = ServingEngine(cfg, mesh_params, ServingConfig(**sc_kw),
                              mesh=mesh)
        contig = ServingEngine(cfg, mesh_params,
                               ServingConfig(**sc_kw, paged_decode=False),
                               mesh=mesh)
        assert paged._paged_loop and not contig._paged_loop
        b = slots
        tokens = jnp.ones((b,), jnp.int32)
        active = jnp.ones((b,), bool)
        # near-full residency so both steps attend real context; every
        # slot gets its own distinct page run (the shuffled-table spirit
        # of the microbench above)
        length = cache_len - page_tokens
        lengths = jnp.full((b,), length, jnp.int32)
        slot_pages = -(-cache_len // page_tokens)
        tables = jnp.asarray(
            _np.arange(b * slot_pages).reshape(b, slot_pages), jnp.int32)

        state = {"arena": paged._kv_store.arena}

        def paged_once():
            logits, state["arena"], _ = paged._paged_step(
                paged.params, tokens, state["arena"], tables, lengths,
                active)
            return logits

        cache = {"c": contig._cache}

        def contig_once():
            logits, cache["c"] = contig._decode(
                contig.params, tokens, cache["c"], active, None, None)
            return logits

        def timed(f):
            f().block_until_ready()  # compile + warm
            t0 = time.perf_counter()
            for _ in range(iters):
                out = f()
            out.block_until_ready()
            return (time.perf_counter() - t0) / iters

        paged_s = timed(paged_once)
        contig_s = timed(contig_once)
        # per-chip decode throughput at this occupancy: b tokens per
        # step over tp chips
        _emit({"metric": "paged_tp_decode_step_us",
               "value": round(paged_s * 1e6, 1), "unit": "us/step",
               "contiguous_us": round(contig_s * 1e6, 1),
               "paged_over_contiguous": round(paged_s / contig_s, 3),
               "paged_tok_s_per_chip": round(b / paged_s / tp, 1),
               "contiguous_tok_s_per_chip": round(b / contig_s / tp, 1),
               "tp": tp, "slots": b, "cache_len": cache_len,
               "attended_tokens": int(length),
               "page_tokens": page_tokens, "int8": int8,
               "arena_devices": len(next(iter(
                   paged._kv_store.arena.values())).sharding.device_set),
               "paged_step_compiles": paged._paged_step._cache_size(),
               "model": cfg.name,
               "backend": jax.default_backend()})


def run_disagg_bench(smoke: bool = False) -> int:
    """Disaggregated serving cells (ISSUE 9).

    Cell 1 — KV handoff throughput: a prompt's full pages leave one paged
    arena through fleet/handoff.py's wire format and adopt into another
    (serialize -> deserialize -> trie adoption), reported as bytes/sec at
    the llama3-8b KV geometry on TPU (a tiny-geometry smoke on CPU). This
    is the payload path a prefill replica pushes to a decode replica.

    Cell 2 (skipped under ``smoke``) — per-role TTFT/ITL through REAL
    engines: a prefill-role engine's hop latency (prefill compute +
    export + serialize), then a decode-role engine that adopted the pages
    serving the same prompt (TTFT with zero-copy adopted KV, ITL from the
    paged decode loop), against a unified engine's cold TTFT — the
    interference number disaggregation exists to improve."""
    _force_platform_from_env()
    import jax
    import jax.numpy as jnp
    import numpy as np
    from k8s_runpod_kubelet_tpu.fleet.handoff import (deserialize_pages,
                                                      serialize_pages)
    from k8s_runpod_kubelet_tpu.workloads.serving.kv_manager import \
        PagedKVStore

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:   # llama3-8b KV geometry: 32 layers, 8 kv heads, hd 128
        layers, hkv, d, t, n_tokens = 32, 8, 128, 16, 2048
        dtype = jnp.bfloat16
    else:
        layers, hkv, d, t, n_tokens = 2, 2, 64, 16, 256
        dtype = jnp.float32
    cache_len = n_tokens
    n_pages = 2 * (n_tokens // t)

    def factory():
        return {"k": jnp.zeros((layers, 1, cache_len, hkv, d), dtype),
                "v": jnp.zeros((layers, 1, cache_len, hkv, d), dtype),
                "index": jnp.zeros((1,), jnp.int32)}

    src, dst = PagedKVStore(n_pages, t, factory), \
        PagedKVStore(n_pages, t, factory)
    tokens = [(i * 17) % 1000 + 1 for i in range(n_tokens)]
    key = jax.random.PRNGKey(0)
    single = {"k": jax.random.normal(key, (layers, 1, cache_len, hkv, d),
                                     dtype),
              "v": jax.random.normal(key, (layers, 1, cache_len, hkv, d),
                                     dtype),
              "index": jnp.asarray([n_tokens], jnp.int32)}
    src.insert(0, tokens, single)
    t0 = time.perf_counter()
    m = src.match_full(0, tokens)
    frags = src.export_pages(m.pages)
    sections = {name: np.asarray(a) for name, a in frags.items()}
    src.release(m.pages)
    blob = serialize_pages(tokens[:m.matched_tokens], t, sections)
    ser_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    header, got = deserialize_pages(blob, expect_page_tokens=t,
                                    expect_sections=dst.section_spec())
    dst.adopt(0, header["tokens"], got)
    adopt_s = time.perf_counter() - t0
    _emit({"metric": "kv_handoff_bytes_per_sec",
           "value": round(len(blob) / (ser_s + adopt_s), 1),
           "unit": "B/s", "bytes": len(blob),
           "pages": header["n_pages"], "page_tokens": t,
           "tokens": n_tokens, "layers": layers, "kv_heads": hkv,
           "head_dim": d, "dtype": np.dtype(dtype).name,
           "serialize_us": round(ser_s * 1e6, 1),
           "adopt_us": round(adopt_s * 1e6, 1),
           "backend": jax.default_backend()})
    if smoke:
        return 0

    # -- cell 2: per-role TTFT/ITL through real engines ----------------------
    from k8s_runpod_kubelet_tpu.workloads.serving import (ServingConfig,
                                                          ServingEngine)
    if on_tpu:
        cfg = _serve_model("llama3-8b")
        params = _serve_params(cfg, 8)
        sc = ServingConfig(slots=8, max_prefill_len=512, cache_len=2048,
                           max_new_tokens=64, quantize_int8=False,
                           kv_page_tokens=16)
        prompt = [(j % 250) + 1 for j in range(1024)]
        new_toks = 64
    else:
        from k8s_runpod_kubelet_tpu.models import tiny_llama
        cfg = tiny_llama(vocab_size=128, embed_dim=64, n_layers=2,
                         n_heads=4, n_kv_heads=2, mlp_dim=128,
                         max_seq_len=512, dtype=jnp.float32,
                         param_dtype=jnp.float32)
        from k8s_runpod_kubelet_tpu.models import init_params
        params = init_params(cfg, jax.random.PRNGKey(0))
        sc = ServingConfig(slots=2, max_prefill_len=32, cache_len=256,
                           max_new_tokens=16, kv_page_tokens=8)
        prompt = [(j % 100) + 1 for j in range(96)]
        new_toks = 12

    def ttft_of(engine, label_prompt) -> float:
        t_sub = time.perf_counter()
        first = []
        engine.submit(label_prompt, max_new_tokens=new_toks,
                      on_token=lambda _t: first.append(
                          time.perf_counter() - t_sub)
                      if not first else None).result(timeout=1800)
        return first[0]

    e_pre = ServingEngine(cfg, params, sc).start()      # prefill role
    e_dec = ServingEngine(cfg, params, sc).start()      # decode role
    e_uni = ServingEngine(cfg, params, sc).start()      # unified contrast
    try:
        # warm every jit with a sequence DISJOINT from the measured
        # prompt: a shared prefix would seed each prefix cache and turn
        # the "cold" unified TTFT into a half-cached prefill, understating
        # the very interference contrast this cell publishes
        warm = [((j * 7) % 89) + 2 for j in range(len(prompt) // 2 + 1)]
        assert warm[:8] != prompt[:8]
        for e in (e_pre, e_dec, e_uni):
            e.submit(warm, max_new_tokens=2).result(timeout=1800)
        t0 = time.perf_counter()
        out = e_pre.export_handoff(prompt)
        hop_s = time.perf_counter() - t0                # the prefill hop
        t0 = time.perf_counter()
        adopted = e_dec.adopt_handoff(out["blob"])
        adopt_s = time.perf_counter() - t0
        ttft_dec = ttft_of(e_dec, prompt)               # adopted KV: hit
        ttft_uni = ttft_of(e_uni, prompt)               # cold: full prefill
        itl = sorted(e_dec.metrics.get_observations(
            "tpu_serving_inter_token_seconds"))
        _emit({"metric": "disagg_ttft_ms", "role": "prefill",
               "value": round(hop_s * 1e3, 2), "unit": "ms",
               "what": "prefill compute + page export + serialize",
               "pages": out["pages"], "bytes": len(out["blob"]),
               "adopt_ms": round(adopt_s * 1e3, 2),
               "adopted_pages": adopted["pages"],
               "model": cfg.name, "backend": jax.default_backend()})
        _emit({"metric": "disagg_ttft_ms", "role": "decode",
               "value": round(ttft_dec * 1e3, 2), "unit": "ms",
               "what": "submit -> first token with adopted (zero-copy) KV",
               "unified_cold_ttft_ms": round(ttft_uni * 1e3, 2),
               "paged_decode_loop": bool(e_dec.debug_snapshot()
                                         .get("paged_decode")),
               "model": cfg.name, "backend": jax.default_backend()})
        _emit({"metric": "disagg_itl_ms", "role": "decode",
               "value": (round(itl[len(itl) // 2] * 1e3, 3) if itl
                         else None),
               "unit": "ms",
               "p95_ms": (round(itl[max(0, int(len(itl) * 0.95) - 1)]
                                * 1e3, 3) if itl else None),
               "steps": len(itl),
               "model": cfg.name, "backend": jax.default_backend()})
    finally:
        e_pre.stop()
        e_dec.stop()
        e_uni.stop()
    return 0


def run_handoff_path_bench(smoke: bool = False) -> int:
    """Device-native vs wire KV handoff cells (ISSUE 11).

    Cell 1 — page-run throughput per path, same arena geometry: a
    prompt's full pages leave one paged arena and adopt into another,
    once through the WIRE codec (device->host gather, numpy
    serialization, deserialize, host->device scatter — exactly the
    /kv_prefill push payload path) and once DEVICE-NATIVE
    (export_pages device buffers adopted directly — zero numpy bytes).
    Both legs block on the destination arena before the clock stops, so
    the device number is real transfer+scatter, not dispatch. Reported
    as bytes/sec per path + the device/wire speedup; the acceptance bar
    is device strictly above wire on the same geometry.

    Cell 2 (skipped under ``smoke``) — two-hop TTFT per path through
    REAL engines: the prefill engine hands a prompt's KV to a decode
    engine over each path (device via the DeviceTransferBus, wire via
    export/serialize/adopt), then the decode engine serves that prompt —
    TTFT includes the hop the way a router-planned two-hop would."""
    _force_platform_from_env()
    import jax
    import jax.numpy as jnp
    import numpy as np
    from k8s_runpod_kubelet_tpu.fleet.handoff import (deserialize_pages,
                                                      serialize_pages)
    from k8s_runpod_kubelet_tpu.workloads.serving.kv_manager import \
        PagedKVStore

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:   # llama3-8b KV geometry: 32 layers, 8 kv heads, hd 128
        layers, hkv, d, t, n_tokens = 32, 8, 128, 16, 2048
        dtype = jnp.bfloat16
    else:
        # KV-heavy CPU geometry (~17MB payload): the wire path's extra
        # legs (host gather copy + serialize + deserialize) must be
        # MATERIAL next to the shared scatter work, or the ratio
        # degenerates into jit-dispatch noise — at chip geometry the
        # payload dwarfs this anyway
        layers, hkv, d, t, n_tokens = 4, 4, 128, 16, 1024
        dtype = jnp.float32
    cache_len = n_tokens
    n_pages = 2 * (n_tokens // t)

    def factory():
        return {"k": jnp.zeros((layers, 1, cache_len, hkv, d), dtype),
                "v": jnp.zeros((layers, 1, cache_len, hkv, d), dtype),
                "index": jnp.zeros((1,), jnp.int32)}

    key = jax.random.PRNGKey(0)
    single = {"k": jax.random.normal(key, (layers, 1, cache_len, hkv, d),
                                     dtype),
              "v": jax.random.normal(key, (layers, 1, cache_len, hkv, d),
                                     dtype),
              "index": jnp.asarray([n_tokens], jnp.int32)}
    tokens = [(i * 17) % 1000 + 1 for i in range(n_tokens)]

    def run_path(device: bool) -> tuple[float, int]:
        """(seconds, payload bytes) for one src-arena -> dst-arena move."""
        src = PagedKVStore(n_pages, t, factory)
        dst = PagedKVStore(n_pages, t, factory)
        src.insert(0, tokens, dict(single))
        jax.block_until_ready(src.arena)
        t0 = time.perf_counter()
        m = src.match_full(0, tokens)
        frags = src.export_pages(m.pages)
        if device:
            src.release(m.pages)
            dst.adopt(0, tokens[:m.matched_tokens], frags)
            nbytes = sum(int(a.size) * int(a.dtype.itemsize)
                         for a in frags.values())
        else:
            sections = {name: np.asarray(a) for name, a in frags.items()}
            src.release(m.pages)
            blob = serialize_pages(tokens[:m.matched_tokens], t, sections)
            header, got = deserialize_pages(
                blob, expect_page_tokens=t,
                expect_sections=dst.section_spec())
            dst.adopt(0, header["tokens"], got)
            nbytes = len(blob)
        jax.block_until_ready(dst.arena)  # the scatter actually landed
        return time.perf_counter() - t0, nbytes

    run_path(device=True)   # warm the gather/adopt jits out of the timings
    run_path(device=False)
    results = {}
    for device in (False, True):
        best = None
        for _ in range(3):
            secs, nbytes = run_path(device)
            if best is None or secs < best[0]:
                best = (secs, nbytes)
        results["device" if device else "wire"] = best
    for path, (secs, nbytes) in results.items():
        _emit({"metric": "handoff_path_bytes_per_sec", "path": path,
               "value": round(nbytes / secs, 1), "unit": "B/s",
               "bytes": nbytes, "seconds": round(secs, 6),
               "pages": n_tokens // t, "page_tokens": t,
               "tokens": n_tokens, "layers": layers, "kv_heads": hkv,
               "head_dim": d, "dtype": np.dtype(dtype).name,
               "backend": jax.default_backend()})
    dev_bps = results["device"][1] / results["device"][0]
    wire_bps = results["wire"][1] / results["wire"][0]
    _emit({"metric": "handoff_path_device_over_wire",
           "value": round(dev_bps / wire_bps, 3), "unit": "x",
           "device_bytes_per_sec": round(dev_bps, 1),
           "wire_bytes_per_sec": round(wire_bps, 1),
           "backend": jax.default_backend()})
    if smoke:
        return 0

    # -- cell 2: two-hop TTFT per path through real engines -------------------
    from k8s_runpod_kubelet_tpu.fleet.device_transfer import (
        BUS, detect_placement_domain, device_push)
    from k8s_runpod_kubelet_tpu.workloads.serving import (ServingConfig,
                                                          ServingEngine)
    if on_tpu:
        cfg = _serve_model("llama3-8b")
        params = _serve_params(cfg, 8)
        sc = ServingConfig(slots=8, max_prefill_len=512, cache_len=2048,
                           max_new_tokens=64, kv_page_tokens=16)
        plen, new_toks = 1024, 32
    else:
        from k8s_runpod_kubelet_tpu.models import init_params, tiny_llama
        cfg = tiny_llama(vocab_size=128, embed_dim=64, n_layers=2,
                         n_heads=4, n_kv_heads=2, mlp_dim=128,
                         max_seq_len=512, dtype=jnp.float32,
                         param_dtype=jnp.float32)
        params = init_params(cfg, jax.random.PRNGKey(0))
        sc = ServingConfig(slots=2, max_prefill_len=32, cache_len=256,
                           max_new_tokens=16, kv_page_tokens=8)
        plen, new_toks = 96, 8

    def prompt_of(salt: int) -> list:
        return [((j * 7 + salt * 131) % (cfg.vocab_size - 2)) + 1
                for j in range(plen)]

    def ttft_of(engine, prompt) -> float:
        t_sub = time.perf_counter()
        first = []
        engine.submit(prompt, max_new_tokens=new_toks,
                      on_token=lambda _t: first.append(
                          time.perf_counter() - t_sub)
                      if not first else None).result(timeout=1800)
        return first[0]

    e_pre = ServingEngine(cfg, params, sc).start()
    e_dw = ServingEngine(cfg, params, sc).start()   # wire-path decoder
    e_dd = ServingEngine(cfg, params, sc).start()   # device-path decoder
    domain = detect_placement_domain()
    BUS.register("bench://decode-device", e_dd, domain)
    try:
        warm = prompt_of(999)
        for e in (e_pre, e_dw, e_dd):
            e.submit(warm, max_new_tokens=2).result(timeout=1800)
        # wire: export+serialize on the prefill engine, adopt on e_dw
        p_w = prompt_of(1)
        t0 = time.perf_counter()
        out = e_pre.export_handoff(p_w)
        e_dw.adopt_handoff(out["blob"])
        hop_wire = time.perf_counter() - t0
        ttft_wire = hop_wire + ttft_of(e_dw, p_w)
        # device: arena-to-arena through the bus
        p_d = prompt_of(2)
        t0 = time.perf_counter()
        dres = device_push(e_pre, "bench://decode-device", p_d,
                           domain=domain)
        jax.block_until_ready(e_dd._kv_store.arena)
        hop_dev = time.perf_counter() - t0
        ttft_dev = hop_dev + ttft_of(e_dd, p_d)
        for path, hop_s, ttft_s, extra in (
                ("wire", hop_wire, ttft_wire, {"bytes": len(out["blob"])}),
                ("device", hop_dev, ttft_dev, {"bytes": dres["bytes"]})):
            _emit({"metric": "handoff_path_two_hop_ttft_ms", "path": path,
                   "value": round(ttft_s * 1e3, 2), "unit": "ms",
                   "hop_ms": round(hop_s * 1e3, 2),
                   "prompt_tokens": plen, **extra,
                   "model": cfg.name, "backend": jax.default_backend()})
    finally:
        BUS.unregister("bench://decode-device")
        e_pre.stop()
        e_dw.stop()
        e_dd.stop()
    return 0


def run_kv_fabric_bench(smoke: bool = False) -> int:
    """Fleet KV fabric cell (ISSUE 16): what a directory pull buys a
    COLD replica, per rung, against the alternative it replaces.

    One owner engine computes a prompt's KV once (its trie holds the
    full-page run the fleet directory would advertise). Three fresh
    cold replicas then each serve the SAME prompt after fetching that
    run through the real /kv_fetch ladder over HTTP — one pinned to
    each rung by the production selection rules (device: owner on this
    process' bus; shm: domains match but the owner is off-bus; wire:
    the owner advertises another placement domain). A fourth fresh
    replica serves the prompt with NO pull — the cold re-prefill every
    rung must beat. Reported TTFT includes the fetch hop (the router
    plans the pull before the request lands, so the hop is on the
    request's critical path exactly like a two-hop handoff).

    The acceptance bar: pull TTFT strictly below cold re-prefill on
    EVERY rung — otherwise the directory consult is pure overhead and
    the fabric should answer misses with a plain re-prefill."""
    _force_platform_from_env()
    import urllib.request

    import jax
    import jax.numpy as jnp
    from k8s_runpod_kubelet_tpu.fleet.device_transfer import (
        BUS, detect_placement_domain)
    from k8s_runpod_kubelet_tpu.workloads.serve_main import serve
    from k8s_runpod_kubelet_tpu.workloads.serving import (ServingConfig,
                                                          ServingEngine)

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = _serve_model("llama3-8b")
        params = _serve_params(cfg, 8)
        # cache sized for three co-resident page runs (each replica's
        # own warm prompt + the pull-warming prompt + the timed prompt)
        sc = ServingConfig(slots=8, max_prefill_len=512, cache_len=4096,
                           max_new_tokens=64, kv_page_tokens=16)
        plen, new_toks = 1024, 32
    else:
        # CPU geometry with MATERIAL prefill compute (wide embed/mlp)
        # next to a modest KV payload — the regime the fabric exists
        # for; the usual 64-wide tiny model prefills a 96-token prompt
        # in single-digit ms, cheaper than ANY transfer, and the cell
        # degenerates into HTTP-overhead noise
        from k8s_runpod_kubelet_tpu.models import init_params, tiny_llama
        cfg = tiny_llama(vocab_size=128, embed_dim=256, n_layers=4,
                         n_heads=8, n_kv_heads=4, mlp_dim=512,
                         max_seq_len=1024, dtype=jnp.float32,
                         param_dtype=jnp.float32)
        params = init_params(cfg, jax.random.PRNGKey(0))
        sc = ServingConfig(slots=2, max_prefill_len=64, cache_len=1024,
                           max_new_tokens=16, kv_page_tokens=8)
        plen, new_toks = 192, 8

    prompt = [((j * 7 + 131) % (cfg.vocab_size - 2)) + 1
              for j in range(plen)]
    warm = [((j * 11 + 977) % (cfg.vocab_size - 2)) + 1
            for j in range(plen)]
    # computed on the OWNER only: the per-rung warm-up pull must really
    # scatter into the cold arena (a prompt the cold replica already
    # holds would dedup in its trie and leave the write jits cold)
    warm_pull = [((j * 13 + 577) % (cfg.vocab_size - 2)) + 1
                 for j in range(plen)]

    def ttft_of(engine, toks) -> float:
        t_sub = time.perf_counter()
        first = []
        engine.submit(toks, max_new_tokens=new_toks,
                      on_token=lambda _t: first.append(
                          time.perf_counter() - t_sub)
                      if not first else None).result(timeout=1800)
        return first[0]

    def fetch(cold_url, own_url, owner_domain, toks) -> tuple[float, dict]:
        """(seconds, reply) for one /kv_fetch POST — the pull hop the
        router puts on the request's critical path."""
        body = json.dumps({"tokens": toks, "owner_url": own_url,
                           "owner_domain": owner_domain,
                           "model": cfg.name}).encode()
        req = urllib.request.Request(
            cold_url + "/kv_fetch", data=body,
            headers={"Content-Type": "application/json"}, method="POST")
        t0 = time.perf_counter()
        with urllib.request.urlopen(req, timeout=1800) as resp:
            out = json.loads(resp.read())
        return time.perf_counter() - t0, out

    dom = detect_placement_domain()
    owner = ServingEngine(cfg, params, sc).start()
    colds = {rung: ServingEngine(cfg, params, sc).start()
             for rung in ("device", "shm", "wire", "reprefill")}
    s_own = serve(owner, port=0, device_domain=dom)
    own_url = f"http://127.0.0.1:{s_own.server_address[1]}"
    servers = {}
    for rung in ("device", "shm", "wire"):
        servers[rung] = serve(colds[rung], port=0, device_domain=dom)
    try:
        for e in (owner, *colds.values()):
            e.submit(warm, max_new_tokens=2).result(timeout=1800)
        owner.submit(warm_pull, max_new_tokens=2).result(timeout=1800)
        owner.submit(prompt, max_new_tokens=2).result(timeout=1800)
        baseline_s = ttft_of(colds["reprefill"], prompt)
        _emit({"metric": "kv_fabric_cold_prefill_ttft_ms",
               "value": round(baseline_s * 1e3, 2), "unit": "ms",
               "prompt_tokens": plen, "model": cfg.name,
               "backend": jax.default_backend()})
        rung_plans = (("device", dom, True),
                      ("shm", dom, False),
                      ("wire", "slice:elsewhere:far-host", False))
        ratios = {}
        for rung, owner_domain, on_bus in rung_plans:
            if on_bus:
                BUS.register(own_url, owner, dom)
            try:
                cold = colds[rung]
                cold_url = (f"http://127.0.0.1:"
                            f"{servers[rung].server_address[1]}")
                # warm this rung's whole machinery (export gather,
                # adopt scatter, the prefix-hit decode) out of the
                # timings with a prompt only the OWNER holds — the
                # baseline's prefill/decode jits got the same
                # treatment above
                _, w_out = fetch(cold_url, own_url, owner_domain,
                                 warm_pull)
                if w_out.get("ok"):
                    ttft_of(cold, warm_pull)
                pull_s, out = fetch(cold_url, own_url, owner_domain,
                                    prompt)
                if not out.get("ok") or out.get("path") != rung:
                    _emit({"metric": "kv_fabric_pull_ttft_ms",
                           "rung": rung, "value": None,
                           "error": f"pull landed on "
                                    f"{out.get('path') or out}"})
                    continue
                ttft_s = pull_s + ttft_of(cold, prompt)
                ratios[rung] = baseline_s / ttft_s
                _emit({"metric": "kv_fabric_pull_ttft_ms", "rung": rung,
                       "value": round(ttft_s * 1e3, 2), "unit": "ms",
                       "pull_ms": round(pull_s * 1e3, 2),
                       "pages": out["pages"],
                       "covered_tokens": out["covered_tokens"],
                       "prompt_tokens": plen, "model": cfg.name,
                       "backend": jax.default_backend()})
            finally:
                if on_bus:
                    BUS.unregister(own_url)
        for rung, ratio in ratios.items():
            _emit({"metric": "kv_fabric_pull_speedup", "rung": rung,
                   "value": round(ratio, 3), "unit": "x",
                   "note": "cold re-prefill TTFT / (pull hop + TTFT); "
                           ">1 means the directory pull paid for itself",
                   "backend": jax.default_backend()})
    finally:
        s_own.shutdown()
        for httpd in servers.values():
            httpd.shutdown()
        owner.stop()
        for e in colds.values():
            e.stop()
    return 0


def run_flight_recorder_bench(smoke: bool = False) -> int:
    """Flight-recorder cell (ISSUE 17): the recorder's own cost, and the
    step-phase/recompile numbers it exists to surface.

    Two fresh engines drain IDENTICAL seeded traffic (varied prompt-length
    buckets, every bucket warmed out of the timings in both arms), one
    with the recorder off and one with it on. The overhead claim is the
    median per-repeat step wall (drain wall / decode steps, both arms
    measured the same external way) — the recorder is an always-on
    surface, so its budget is noise (< 2%). The enabled arm then reports
    what the ring saw: per-phase p50s from the rollup (see BENCH_NOTES on
    async-dispatch honesty for the kernel phase), the watchdog's
    post-warmup recompile count over alarmed hot-path jits (non-zero on
    steady traffic = the PR 12 cache-key-flap class, fails the cell), and
    the ring's byte occupancy against its budget."""
    _force_platform_from_env()
    import jax
    import jax.numpy as jnp
    from k8s_runpod_kubelet_tpu.workloads.serving import (ServingConfig,
                                                          ServingEngine)

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = _serve_model("llama3-8b")
        params = _serve_params(cfg, 8)
        base = dict(slots=8, max_prefill_len=512, cache_len=2048,
                    max_new_tokens=64)
        plens, new_toks, repeats = (64, 192, 384), 48, 7
    else:
        # widened CPU geometry (the kv_fabric lesson): the recorder's
        # per-step cost is FIXED, so against the 64-wide toy model's
        # ~2ms step it reads as several percent of nothing — a step must
        # carry material compute for the overhead fraction to mean what
        # it means on a chip
        from k8s_runpod_kubelet_tpu.models import init_params, tiny_llama
        cfg = tiny_llama(vocab_size=128, embed_dim=256, n_layers=4,
                         n_heads=8, n_kv_heads=4, mlp_dim=512,
                         max_seq_len=512, dtype=jnp.float32,
                         param_dtype=jnp.float32)
        params = init_params(cfg, jax.random.PRNGKey(0))
        base = dict(slots=4, max_prefill_len=64, cache_len=512,
                    max_new_tokens=32)
        plens, new_toks, repeats = (12, 24, 48), 16, (5 if smoke else 9)

    def prompts_for(r: int) -> list[list[int]]:
        # varied traffic: every repeat cycles the prompt-length buckets
        # and shifts token values, so the compile-once claim is tested
        # against shape variety, not one cached signature
        return [[((j * 7 + 31 * (r + 1) + i) % (cfg.vocab_size - 2)) + 1
                 for j in range(plen)]
                for i, plen in enumerate(plens)]

    engines = {}
    for enabled in (False, True):
        sc = ServingConfig(flight_recorder=enabled, **base)
        engines[enabled] = ServingEngine(cfg, params, sc).start()
    per_repeat = {False: [], True: []}
    try:
        # warm every prompt-length bucket out of the timings — both arms
        # identically, so compiles never skew the delta
        for e in engines.values():
            for toks in prompts_for(0):
                e.submit(toks, max_new_tokens=4).result(timeout=1800)
        # INTERLEAVED repeats (disabled, enabled, disabled, ...): the two
        # arms sample the same machine state — a sequential A-then-B run
        # lets thermal/allocator drift between the arms masquerade as
        # recorder overhead several times the real cost
        for r in range(1, repeats + 1):
            batch = prompts_for(r)
            for enabled in (False, True):
                e = engines[enabled]
                s0 = e.metrics.get_counter("tpu_serving_decode_steps")
                t0 = time.perf_counter()
                futs = [e.submit(toks, max_new_tokens=new_toks)
                        for toks in batch]
                for f in futs:
                    f.result(timeout=1800)
                wall = time.perf_counter() - t0
                steps = (e.metrics.get_counter("tpu_serving_decode_steps")
                         - s0)
                if steps:
                    per_repeat[enabled].append(wall / steps)
        dis, en = {}, {}
        for enabled, out in ((False, dis), (True, en)):
            vals = sorted(per_repeat[enabled])
            out["step_ms_median"] = vals[len(vals) // 2] * 1e3
        en["rollup"] = engines[True].recorder.rollup()
        wd = engines[True].watchdog.snapshot()
        # bucketed fns (budget=None) legitimately compile once per
        # prompt-length bucket; only alarmed fns count
        en["recompiles_alarmed"] = sum(
            t["recompiles"] for t in wd.values()
            if t["budget"] is not None)
        en["watchdog"] = wd
    finally:
        for e in engines.values():
            e.stop()
    backend = jax.default_backend()
    _emit({"metric": "fr_step_ms", "arm": "disabled",
           "value": round(dis["step_ms_median"], 4), "unit": "ms",
           "model": cfg.name, "backend": backend})
    _emit({"metric": "fr_step_ms", "arm": "enabled",
           "value": round(en["step_ms_median"], 4), "unit": "ms",
           "model": cfg.name, "backend": backend})
    overhead = ((en["step_ms_median"] - dis["step_ms_median"])
                / dis["step_ms_median"])
    _emit({"metric": "fr_overhead_frac", "value": round(overhead, 4),
           "unit": "frac",
           "note": "median step wall (enabled - disabled) / disabled on "
                   "identical seeded traffic; acceptance < 0.02",
           "backend": backend})
    roll = en["rollup"]
    for p in ("schedule", "kernel", "sample", "commit"):
        _emit({"metric": "fr_phase_p50_ms", "phase": p,
               "value": round(roll.get(f"{p}_ms_p50", 0.0), 4),
               "unit": "ms", "backend": backend})
    _emit({"metric": "fr_recompiles", "value": en["recompiles_alarmed"],
           "unit": "count",
           "note": "post-warmup recompiles of ALARMED hot-path jits "
                   "across the varied-traffic soak; non-zero = cache-key "
                   "flap (the PR 12 class)",
           "watchdog": en["watchdog"], "backend": backend})
    _emit({"metric": "fr_ring_hwm_bytes", "value": roll.get("bytes", 0),
           "unit": "B", "budget": roll.get("max_bytes", 0),
           "records": roll.get("records", 0),
           "dropped": roll.get("dropped", 0),
           "note": "ring occupancy after the soak vs the byte budget "
                   "(the double bound holds at every append)",
           "backend": backend})
    return 0


def run_cost_bench(smoke: bool = False) -> int:
    """Cost-attribution cell (ISSUE 20): the meter's three acceptance
    bars, measured through real engines draining real traffic.

    (a) TELESCOPE: the meter derives per-phase chip-seconds from the
        engine's internal span stamps; this cell times the SAME requests
        from outside (perf_counter before submit, a done-callback at
        completion) and checks attributed chip-seconds == external wall
        x chips within 1%. The two clocks only agree if the monotone
        boundary clamp loses nothing.
    (b) OVERHEAD: meter on vs off on identical seeded traffic,
        interleaved repeats, median per-step wall — the meter folds one
        ledger entry per COMPLETED request (never per token or step), so
        its budget is the flight-recorder bar: < 2%.
    (c) IDLE BURN: a saturated arm (queue never empty from meter birth
        to last completion) must attribute ~all paid chip-seconds; the
        idle gauge reading non-zero under saturation would mean the
        meter leaks paid time it should be attributing."""
    _force_platform_from_env()
    import jax
    import jax.numpy as jnp
    from k8s_runpod_kubelet_tpu.workloads.serving import (ServingConfig,
                                                          ServingEngine)

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = _serve_model("llama3-8b")
        params = _serve_params(cfg, 8)
        base = dict(slots=8, max_prefill_len=512, cache_len=2048,
                    max_new_tokens=64)
        plens, new_toks, repeats = (64, 192, 384), 48, 7
    else:
        # same widened CPU geometry as the flight-recorder cell: the
        # meter's per-request cost is FIXED, so a step must carry
        # material compute for the overhead fraction to mean what it
        # means on a chip
        from k8s_runpod_kubelet_tpu.models import init_params, tiny_llama
        cfg = tiny_llama(vocab_size=128, embed_dim=256, n_layers=4,
                         n_heads=8, n_kv_heads=4, mlp_dim=512,
                         max_seq_len=512, dtype=jnp.float32,
                         param_dtype=jnp.float32)
        params = init_params(cfg, jax.random.PRNGKey(0))
        base = dict(slots=4, max_prefill_len=64, cache_len=512,
                    max_new_tokens=32)
        plens, new_toks, repeats = (12, 24, 48), 16, (6 if smoke else 10)
    backend = jax.default_backend()

    def prompts_for(r: int) -> list[list[int]]:
        return [[((j * 7 + 31 * (r + 1) + i) % (cfg.vocab_size - 2)) + 1
                 for j in range(plen)]
                for i, plen in enumerate(plens)]

    # --- (b) overhead: meter off vs on, interleaved identical traffic ---
    engines = {}
    for enabled in (False, True):
        sc = ServingConfig(cost_meter=enabled, **base)
        engines[enabled] = ServingEngine(cfg, params, sc).start()
    per_repeat = {False: [], True: []}
    try:
        for e in engines.values():  # warm every bucket out of the timings
            for toks in prompts_for(0):
                e.submit(toks, max_new_tokens=4).result(timeout=1800)
        # interleaved repeats (the flight-recorder lesson): both arms
        # sample the same machine state, so drift never reads as
        # overhead. The arm ORDER alternates per repeat and the headline
        # is the median of PAIRED per-repeat ratios — within-repeat
        # pairing cancels slow-machine windows that a median of absolute
        # walls would misread as meter cost
        ratios = []
        for r in range(1, repeats + 1):
            batch = prompts_for(r)
            wall_per_step = {}
            order = (False, True) if r % 2 else (True, False)
            for enabled in order:
                e = engines[enabled]
                s0 = e.metrics.get_counter("tpu_serving_decode_steps")
                t0 = time.perf_counter()
                futs = [e.submit(toks, max_new_tokens=new_toks)
                        for toks in batch]
                for f in futs:
                    f.result(timeout=1800)
                wall = time.perf_counter() - t0
                steps = (e.metrics.get_counter("tpu_serving_decode_steps")
                         - s0)
                if steps:
                    per_repeat[enabled].append(wall / steps)
                    wall_per_step[enabled] = wall / steps
            if len(wall_per_step) == 2:
                ratios.append(wall_per_step[True] / wall_per_step[False])
    finally:
        for e in engines.values():
            e.stop()
    med = {en: sorted(v)[len(v) // 2] * 1e3
           for en, v in per_repeat.items()}
    overhead = sorted(ratios)[len(ratios) // 2] - 1.0
    _emit({"metric": "cost_step_ms", "arm": "disabled",
           "value": round(med[False], 4), "unit": "ms",
           "model": cfg.name, "backend": backend})
    _emit({"metric": "cost_step_ms", "arm": "enabled",
           "value": round(med[True], 4), "unit": "ms",
           "model": cfg.name, "backend": backend})
    _emit({"metric": "cost_meter_overhead_frac", "value": round(overhead, 4),
           "unit": "frac",
           "note": "median PAIRED per-repeat step-wall ratio (metered / "
                   "unmetered - 1) on identical seeded traffic, arm order "
                   "alternating; acceptance < 0.02",
           "backend": backend})

    # --- (a) telescope + (c) idle burn: one fresh saturated engine ---
    # Every request from meter birth is timed externally; the whole
    # stream is queued at once so the engine is never idle until the
    # last completion.
    walls: list[float] = []  # list.append is atomic; callbacks race safely
    sat = ServingEngine(cfg, params,
                        ServingConfig(cost_meter=True, **base)).start()
    try:
        futs = []
        for r in range(repeats + 1):
            for toks in prompts_for(r):
                t0 = time.perf_counter()
                f = sat.submit(toks, max_new_tokens=new_toks)
                # the done-callback fires in the engine thread right
                # after metering, so the external wall closes at (not
                # after) completion even when futures finish out of the
                # wait order below
                f.add_done_callback(
                    lambda _f, t0=t0:
                    walls.append(time.perf_counter() - t0))
                futs.append(f)
        for f in futs:
            f.result(timeout=1800)
        snap = sat.costmeter.snapshot()  # before stop(): idle still live
    finally:
        sat.stop()
    attributed = sum(snap["totals"]["chip_seconds"].values())
    expected = sum(walls) * snap["chips"]
    telescope_err = abs(attributed - expected) / expected
    idle_frac = (snap["idle_chip_seconds"]
                 / max(snap["paid_chip_seconds"], 1e-9))
    tokens = snap["totals"]["tokens"]
    _emit({"metric": "cost_telescope_err_frac",
           "value": round(telescope_err, 6), "unit": "frac",
           "attributed_chip_s": round(attributed, 4),
           "external_chip_s": round(expected, 4),
           "requests": snap["totals"]["requests"],
           "note": "meter-attributed chip-seconds vs externally timed "
                   "submit->done walls x chips; acceptance < 0.01",
           "backend": backend})
    _emit({"metric": "cost_idle_burn_frac", "value": round(idle_frac, 6),
           "unit": "frac",
           "paid_chip_s": snap["paid_chip_seconds"],
           "idle_chip_s": snap["idle_chip_seconds"],
           "note": "idle/paid on the saturated arm (queue never empty); "
                   "acceptance < 0.05",
           "backend": backend})
    _emit({"metric": "cost_dollars_per_mtok",
           "value": round(snap["totals"]["cost_dollars"]
                          / max(tokens, 1) * 1e6, 4),
           "unit": "$/Mtok", "model": cfg.name,
           "generation": snap["generation"],
           "price_per_chip_hr": snap["price_per_chip_hr"],
           "tokens": tokens,
           "note": "generated tokens only; CPU rows price the wall at "
                   "the fallback list price — the headline needs a chip",
           "backend": backend})
    ok = telescope_err < 0.01 and overhead < 0.02 and idle_frac < 0.05
    return 0 if ok else 1


def run_chunked_bench(smoke: bool = False) -> int:
    """Chunked-prefill + streamed-handoff cells (ISSUE 10).

    Cell 1 — TTFT-vs-prompt-length sweep, serial vs streamed two-hop:
    for each prompt length, the SERIAL path is PR 9's stacked pipeline
    (prefill compute + export + serialize, THEN adopt, THEN decode-side
    TTFT) and the STREAMED path runs export_handoff_stream with a sender
    thread serializing + adopting each chunk frame while the next chunk
    computes. Each length reports both two-hop TTFTs (min of ``reps``
    runs — scheduler noise must not masquerade as overlap), the realized
    overlap ratio, and streamed/serial. The claim the CPU smoke pins:
    streamed < serial at the longest prompt — the overlap is real even
    in-process, because serialization/adoption are C-level work that
    releases the GIL under the compute.

    The inter-replica hop crosses an EMULATED LINK (a store-and-forward
    proxy pacing bytes at ``link_gbps`` — labeled on every line): real
    disaggregated fleets move KV across a pod network, and that wire time
    is precisely what the stream hides behind compute. In-process
    localhost alone has no wire (and a 1-core host has no second core to
    overlap CPU work onto), so without the labeled link model the cell
    would measure scheduler noise, not the overlap it exists to pin. The
    pipeline under test is the production code end to end — serve_main
    handlers, sender thread, chunk frames, assembler — only the wire is
    modeled.

    Cell 2 — ITL under long prefill: a decode stream is mid-generation
    when a long prompt is admitted; max inter-token gap with chunking ON
    (decode steps interleave between chunks) vs OFF (the monolithic
    prefill monopolizes the device). Chunked must bound the spike the
    monolithic engine reproduces."""
    _force_platform_from_env()
    import json as _json
    import statistics
    import urllib.error
    import urllib.request
    import jax
    import jax.numpy as jnp
    from k8s_runpod_kubelet_tpu.workloads.serve_main import serve
    from k8s_runpod_kubelet_tpu.workloads.serving import (ServingConfig,
                                                          ServingEngine)

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = _serve_model("llama3-8b")
        params = _serve_params(cfg, 8)
        page_t, chunk_t, max_pref, cache_len = 16, 256, 512, 8192
        lengths = [1024, 2048, 4096] if not smoke else [1024, 4096]
        slots, reps, new_toks = 8, 3, 32
    else:
        # KV-HEAVY tiny geometry (full-MHA 8x64 heads over a small MLP):
        # the transfer leg must be material next to compute, or the
        # overlap claim degenerates into dispatch-overhead noise — at 8B
        # scale KV bytes/token dwarf this ratio anyway
        from k8s_runpod_kubelet_tpu.models import init_params, tiny_llama
        cfg = tiny_llama(vocab_size=256, embed_dim=128, n_layers=4,
                         n_heads=8, n_kv_heads=8, head_dim=64, mlp_dim=128,
                         max_seq_len=1024, dtype=jnp.float32,
                         param_dtype=jnp.float32)
        params = init_params(cfg, jax.random.PRNGKey(0))
        page_t, chunk_t, max_pref, cache_len = 8, 128, 128, 960
        lengths = [96, 224, 448] if not smoke else [96, 448]
        slots, reps, new_toks = 2, 5, 8

    def make_engine(chunk: int) -> ServingEngine:
        sc = ServingConfig(slots=slots, max_prefill_len=max_pref,
                           cache_len=cache_len, max_new_tokens=64,
                           kv_page_tokens=page_t,
                           serving_chunk_tokens=chunk)
        return ServingEngine(cfg, params, sc).start()

    def prompt_of(length: int, salt: int) -> list:
        v = cfg.vocab_size - 2
        return [((j * 7 + salt * 131) % v) + 1 for j in range(length)]

    def ttft_of(engine, prompt) -> float:
        t_sub = time.perf_counter()
        first = []
        engine.submit(prompt, max_new_tokens=new_toks,
                      on_token=lambda _t: first.append(
                          time.perf_counter() - t_sub)
                      if not first else None).result(timeout=1800)
        return first[0]

    # -- cell 1: serial vs streamed two-hop TTFT sweep, over the REAL
    # serve_main HTTP surface (the production path: /kv_prefill on the
    # prefill replica pushing to the decode replica — monolithic blob
    # push from the chunking-off engine, chunk-frame stream from the
    # chunking-on engine) -----------------------------------------------------
    # per-host DCN share on TPU; a deliberately CONSERVATIVE shared-pod
    # link for the CPU smoke — the smoke's job is to pin the overlap
    # MECHANISM on a small noisy host, which needs the wire leg to
    # dominate scheduler jitter (the rate is labeled on every line; the
    # chip run models the faster real DCN)
    link_gbps = 8.0 if on_tpu else 0.2
    link_rtt_s = 0.0003
    e_ser = make_engine(0)          # serial prefill side (monolithic hop)
    e_str = make_engine(chunk_t)    # streamed prefill side (chunked)
    e_dse = make_engine(0)          # decode side for the serial hops
    e_dst = make_engine(0)          # decode side for the streamed hops
    engines = [e_ser, e_str, e_dse, e_dst]
    servers = [serve(e, port=0) for e in engines]
    urls = [f"http://127.0.0.1:{s.server_address[1]}" for s in servers]

    def link_proxy(target: str):
        """The emulated inter-replica wire: forward each POST with a
        sleep budget of rtt + bytes/rate. The sleep is pure wait (socket
        time on a real link) — compute proceeds under it, which is
        exactly the overlap streamed handoff monetizes. The proxy keeps
        ONE persistent downstream connection per inbound connection
        (Nagle off on both hops): a real NIC has no per-frame
        connection-setup cost, and paying one here 4x per stream vs 1x
        per blob would charge the streamed path an emulation artifact,
        not wire time."""
        import http.client
        import socket as _socket
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        parsed = urllib.parse.urlsplit(target)

        class _Link(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            disable_nagle_algorithm = True

            def log_message(self, *a):
                pass

            def setup(self):
                super().setup()
                self._down = None

            def finish(self):
                if self._down is not None:
                    self._down.close()
                super().finish()

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n) if n else b""
                time.sleep(link_rtt_s + len(body) * 8 / (link_gbps * 1e9))
                if self._down is None:
                    self._down = http.client.HTTPConnection(
                        parsed.hostname, parsed.port or 80, timeout=1800)
                    self._down.connect()
                    self._down.sock.setsockopt(_socket.IPPROTO_TCP,
                                               _socket.TCP_NODELAY, 1)
                self._down.request(
                    "POST", self.path, body=body,
                    headers={k: v for k, v in self.headers.items()
                             if k.lower() in ("content-type",
                                              "traceparent")})
                resp = self._down.getresponse()
                out, status = resp.read(), resp.status
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(out)))
                self.end_headers()
                self.wfile.write(out)

        httpd = ThreadingHTTPServer(("127.0.0.1", 0), _Link)
        httpd.daemon_threads = True
        import threading as _threading
        _threading.Thread(target=httpd.serve_forever, daemon=True).start()
        return httpd, f"http://127.0.0.1:{httpd.server_address[1]}"

    proxies = [link_proxy(urls[2]), link_proxy(urls[3])]
    link_urls = {2: proxies[0][1], 3: proxies[1][1]}

    def hop(pre_idx: int, dec_idx: int, prompt) -> dict:
        body = _json.dumps({"path": "/generate",
                            "request": {"tokens": prompt},
                            "handoff_to": link_urls[dec_idx]}).encode()
        req = urllib.request.Request(
            urls[pre_idx] + "/kv_prefill", data=body,
            headers={"Content-Type": "application/json"}, method="POST")
        t0 = time.perf_counter()
        with urllib.request.urlopen(req, timeout=1800) as resp:
            out = _json.loads(resp.read())
        out["hop_s"] = time.perf_counter() - t0
        if not out.get("ok"):
            raise RuntimeError(f"hop failed: {out}")
        return out

    try:
        warm = prompt_of(max(lengths), salt=999)
        for e in engines:
            e.submit(warm, max_new_tokens=2).result(timeout=1800)
        # warm the hop jits/buckets end to end (export/serialize/adopt)
        hop(0, 2, prompt_of(max(lengths), salt=998))
        hop(1, 3, prompt_of(max(lengths), salt=997))
        for li, length in enumerate(lengths):
            serial_ms, streamed_ms = [], []
            chunks, overlap = 0, None
            for rep in range(reps):
                # fresh prompt per rep/mode: a prefix hit would turn the
                # measured hop into a cache read
                p_s = prompt_of(length, salt=li * 100 + rep)
                out = hop(0, 2, p_s)
                serial_ms.append((out["hop_s"] + ttft_of(e_dse, p_s)) * 1e3)
                p_t = prompt_of(length, salt=li * 100 + rep + 50)
                out = hop(1, 3, p_t)
                chunks = out.get("chunks", 0)
                overlap = out.get("overlap_ratio")
                streamed_ms.append((out["hop_s"]
                                    + ttft_of(e_dst, p_t)) * 1e3)
            # headline = MEDIANS: on a small/shared host one descheduled
            # rep swings a min by tens of ms; the claim must survive noise
            s_med = statistics.median(serial_ms)
            t_med = statistics.median(streamed_ms)
            _emit({"metric": "chunked_two_hop_ttft_ms",
                   "prompt_tokens": length,
                   "serial_ms": round(s_med, 2),
                   "streamed_ms": round(t_med, 2),
                   "streamed_over_serial": round(t_med / s_med, 3),
                   "serial_ms_best": round(min(serial_ms), 2),
                   "streamed_ms_best": round(min(streamed_ms), 2),
                   "chunks": chunks, "overlap_ratio": overlap,
                   "chunk_tokens": chunk_t,
                   "page_tokens": page_t, "reps": reps,
                   "emulated_link": True, "link_gbps": link_gbps,
                   "link_rtt_ms": round(link_rtt_s * 1e3, 3),
                   "model": cfg.name,
                   "backend": jax.default_backend()})
    finally:
        for httpd, _u in proxies:
            httpd.shutdown()
        for s in servers:
            s.shutdown()
        for e in engines:
            e.stop()

    # -- cell 2: ITL under long prefill, chunked vs monolithic ---------------
    long_prompt = prompt_of(max(lengths), salt=7)
    results = {}
    for label, chunk in (("chunked", chunk_t), ("monolithic", 0)):
        e = make_engine(chunk)
        try:
            e.submit(prompt_of(max(lengths), salt=997),
                     max_new_tokens=2).result(timeout=1800)
            gaps: list = []
            last = [None]

            def on_token(_t):
                now = time.perf_counter()
                if last[0] is not None:
                    gaps.append(now - last[0])
                last[0] = now

            stream_fut = e.submit(prompt_of(8, salt=5),
                                  max_new_tokens=48, on_token=on_token)
            while not gaps:           # the stream is actually decoding
                time.sleep(0.005)
            e.submit(long_prompt, max_new_tokens=2).result(timeout=1800)
            stream_fut.result(timeout=1800)
            results[label] = {
                "max_gap_ms": round(max(gaps) * 1e3, 2),
                "p50_gap_ms": round(statistics.median(gaps) * 1e3, 3),
                "interleaved_steps": e.metrics.get_counter(
                    "tpu_serving_chunk_interleaved_steps"),
            }
        finally:
            e.stop()
    _emit({"metric": "chunked_itl_under_prefill_ms",
           "value": results["chunked"]["max_gap_ms"],
           "unit": "ms (max co-resident ITL gap during a "
                   f"{max(lengths)}-token prefill)",
           "chunked": results["chunked"],
           "monolithic": results["monolithic"],
           "chunk_tokens": chunk_t, "model": cfg.name,
           "backend": jax.default_backend()})
    return 0


def run_ring_flash_check() -> int:
    """TPU verification for ring flash attention (ROUND3_NOTES step 6b).

    Single chip cannot run a multi-device ring, but it CAN lower and run the
    exact per-device program the ring executes: ``_ring_flash`` (streamed
    Pallas chunk kernels under lax.cond/scan inside a custom VJP) via
    shard_map over a 1-device seq mesh — the composition interpret mode
    can't validate. Parity vs flash_attention (same math at n=1) for fwd AND
    grads, then fwd+bwd timing vs the plain flash kernel (ring overhead at
    n=1 should be noise). With >=2 chips: real ring, parity vs the XLA
    einsum ring, plus timing."""
    _force_platform_from_env()
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    import importlib
    # the package re-exports ring_attention the FUNCTION; we need the module
    ra = importlib.import_module("k8s_runpod_kubelet_tpu.ops.ring_attention")
    from k8s_runpod_kubelet_tpu.ops.attention import flash_attention
    from k8s_runpod_kubelet_tpu.parallel import MeshConfig, make_mesh

    if jax.default_backend() != "tpu":
        _emit({"metric": "ring_flash_check", "value": None,
               "error": f"needs a TPU, got {jax.default_backend()!r}"})
        return 1

    n = jax.device_count()
    b, hq, hkv, s, d = 1, 8, 4, 4096, 128
    ks = jax.random.split(jax.random.PRNGKey(0), 4)
    q = jax.random.normal(ks[0], (b, hq, s, d), jnp.bfloat16)
    k = jax.random.normal(ks[1], (b, hkv, s, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, hkv, s, d), jnp.bfloat16)
    g = jax.random.normal(ks[3], (b, hq, s, d), jnp.bfloat16)
    scale = d ** -0.5

    def timed(fn, iters=10):
        fn(q, k, v)[0].block_until_ready()
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(q, k, v)
        jax.tree_util.tree_leaves(out)[0].block_until_ready()
        return (time.perf_counter() - t0) / iters

    def fwd_bwd(attn):
        def run(q, k, v):
            out, pull = jax.vjp(attn, q, k, v)
            return pull(g)
        return jax.jit(run)

    if n >= 2:
        mesh = make_mesh(MeshConfig(data=1, seq=n))
        flash = lambda q, k, v: ra.ring_attention(  # noqa: E731
            q, k, v, mesh, causal=True, use_flash=True)
        xla_ring = lambda q, k, v: ra.ring_attention(  # noqa: E731
            q, k, v, mesh, causal=True)
        ref_fn, mode = xla_ring, f"ring_n{n}"
    else:
        mesh = make_mesh(MeshConfig(data=1, seq=1))
        s_local = s
        bq, bk = ra.tuned_block_sizes(s_local, s_local)

        def local_flash(qs, ks_, vs):
            idx = jax.lax.axis_index(ra.AXES.SEQ)
            return ra._ring_flash(qs, ks_, vs, idx, n=1, axis=ra.AXES.SEQ,
                                  scale=scale, window=None, soft_cap=None,
                                  block_q=bq, block_k=bk, interpret=False)

        spec = P(None, None, ra.AXES.SEQ, None)
        flash = ra.shard_map_compat(local_flash, mesh=mesh,
                                    in_specs=(spec, spec, spec),
                                    out_specs=spec)
        ref_fn = lambda q, k, v: flash_attention(  # noqa: E731
            q, k, v, causal=True, sm_scale=scale, use_pallas=True)
        mode = "single_chip_ring_body"

    # fwd parity
    got = jax.jit(flash)(q, k, v)
    ref = jax.jit(ref_fn)(q, k, v)
    fwd_err = float(jnp.max(jnp.abs(got.astype(jnp.float32)
                                    - ref.astype(jnp.float32))))
    # grad parity (the custom VJP vs autodiff of the reference path);
    # bind the jitted fwd+bwd ONCE each so parity + timing share compiles
    flash_fb, ref_fb = fwd_bwd(flash), fwd_bwd(ref_fn)
    got_g = flash_fb(q, k, v)
    ref_g = ref_fb(q, k, v)
    grad_err = max(float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                         - b.astype(jnp.float32))))
                   for a, b in zip(got_g, ref_g))
    t_flash = timed(flash_fb)
    t_ref = timed(ref_fb)
    ok = bool(np.isfinite(fwd_err) and fwd_err < 0.08
              and np.isfinite(grad_err) and grad_err < 0.25)  # bf16 ulps
    _emit({"metric": "ring_flash_check", "value": round(t_flash * 1e3, 3),
           "unit": "ms", "mode": mode, "chips": n, "seq_len": s,
           "fwd_max_abs_err": round(fwd_err, 4),
           "grad_max_abs_err": round(grad_err, 4),
           "ref_ms": round(t_ref * 1e3, 3), "parity_ok": ok})
    return 0 if ok else 1


def _arg_value(flag: str, default: str) -> str:
    if flag in sys.argv:
        i = sys.argv.index(flag)
        if i + 1 < len(sys.argv):
            return sys.argv[i + 1]
    return default


def _serve_model(name: str):
    """Bench model configs. 'llama3-8b' is the BASELINE.md headline geometry
    ("tokens/sec/chip at 8B"); throughput is weight-value-independent, so
    random/zero init is honest for perf (zero egress: no real checkpoints)."""
    from k8s_runpod_kubelet_tpu.models import (gemma2_9b, llama3_8b,
                                               mistral_7b)
    from __graft_entry__ import _bench_config
    if name == "bench-260m":
        return _bench_config(tiny=False)
    if name == "tiny":
        return _bench_config(tiny=True)
    from k8s_runpod_kubelet_tpu.models import mla_8b
    table = {"llama3-8b": llama3_8b, "mistral-7b": mistral_7b,
             "gemma2-9b": gemma2_9b, "mla-8b": mla_8b}
    if name not in table:  # parseable error, not a KeyError traceback
        _emit({"metric": "serving_tokens_per_sec", "value": None,
               "error": f"unknown --model {name!r}; choose from "
                        f"{['tiny', 'bench-260m'] + sorted(table)}"})
        raise SystemExit(1)
    return table[name]()


def _serve_params(cfg, bits: int):
    """DEVICE-ready param tree for serving benches, HBM-safe for 8B on one
    16GB v5e: big trees are built as HOST zeros (eval_shape + np.zeros =
    copy-on-write pages, no 32GB resident). With ``bits`` 8 or 4 the tree
    is quantized leaf-by-leaf onto the device here — the full-precision
    tree never sits in HBM next to the quantized copy (same strategy as
    serve_main --int8/--int4); bits=0 device_puts the zeros once (an
    un-quantized 8B genuinely doesn't fit a 16GB chip — that OOM is honest
    and loud)."""
    import jax
    import numpy as np
    from k8s_runpod_kubelet_tpu.models import init_params

    if not bits and cfg.param_count < 1e9:
        return init_params(cfg, jax.random.PRNGKey(0))
    shapes = jax.eval_shape(
        lambda: init_params(cfg, jax.random.PRNGKey(0)))
    host = jax.tree_util.tree_map(
        lambda sd: np.zeros(sd.shape, sd.dtype), shapes)
    if bits:
        from k8s_runpod_kubelet_tpu.models.quant import quantize_params
        return quantize_params(cfg, host, bits=bits)
    return jax.device_put(host)


def serve_once(model: str, *, slots: int, n_req: int, new_toks: int,
               cache_len: int, prompt_len: int, int8: bool, kv_int8: bool,
               speculate_k: int, donate: bool = True, params=None,
               label: str = "", int4: bool = False) -> dict:
    """One serving measurement; returns the result dict (not emitted)."""
    import jax
    from k8s_runpod_kubelet_tpu.workloads.serving import (ServingConfig,
                                                          ServingEngine)

    cfg = _serve_model(model)
    if params is None:
        params = _serve_params(cfg, 4 if int4 else (8 if int8 else 0))
    # _serve_params already quantized when int8 (and _mm dispatches on the
    # leaf structure), so the engine must NOT quantize again — the flag
    # survives only as a record label
    sc = ServingConfig(slots=slots, max_prefill_len=min(cache_len // 2, 512),
                       cache_len=cache_len, max_new_tokens=new_toks,
                       quantize_int8=False, quantize_kv_int8=kv_int8,
                       speculate_k=speculate_k, donate_cache=donate)
    engine = ServingEngine(cfg, params, sc).start()
    try:
        engine.submit([1, 2, 3], max_new_tokens=2).result(timeout=1800)  # warm
        t0 = time.perf_counter()
        first_tok = {}

        def on_first(i, t_sub):
            def cb(_tok):
                first_tok.setdefault(i, time.perf_counter() - t_sub)
            return cb

        futs = []
        for i in range(n_req):
            prompt = [(j % 250) + 1 for j in range(1 + (i * 37) % prompt_len)]
            # per-request submit stamp: TTFT is THIS request's submit ->
            # first token (a shared t0 would fold earlier submits' wall
            # time into later requests' numbers)
            futs.append(engine.submit(prompt, max_new_tokens=new_toks,
                                      on_token=on_first(
                                          i, time.perf_counter())))
        peak_queue = max(engine.queue_depth, 1)
        outs = [f.result(timeout=1800) for f in futs]
        wall = time.perf_counter() - t0
        accepted = proposed = None
        if speculate_k:
            accepted = engine.metrics.get_counter("tpu_serving_spec_accepted")
            proposed = engine.metrics.get_counter("tpu_serving_spec_proposed")
        # paged prefix pool (ISSUE 8): the bench prompts share long heads
        # ([1, 2, 3...] prefixes), so the cross-request hit rate here is a
        # real number, not a synthetic one
        kv_stats = engine.prefix_cache_stats()
        pc_hits = engine.metrics.get_counter("tpu_serving_prefix_cache_hits")
        pc_misses = engine.metrics.get_counter(
            "tpu_serving_prefix_cache_misses")
    finally:
        engine.stop()
    toks = sum(len(o["tokens"]) for o in outs)
    lats = sorted(o["latency_s"] for o in outs)
    # TTFT is queue-inclusive (submit -> first token), the user-felt number
    ttfts = sorted(first_tok.values())
    rec = {
        "metric": "serving_tokens_per_sec",
        "value": round(toks / wall, 1),
        "unit": "tok/s",
        "p50_latency_s": round(lats[len(lats) // 2], 3),
        "p99_latency_s": round(lats[min(len(lats) - 1,
                                        int(len(lats) * 0.99))], 3),
        "p50_ttft_s": round(ttfts[len(ttfts) // 2], 3) if ttfts else None,
        "p99_ttft_s": (round(ttfts[min(len(ttfts) - 1,
                                       int(len(ttfts) * 0.99))], 3)
                       if ttfts else None),
        "requests": n_req, "slots": slots,
        "new_tokens_per_request": new_toks,
        "cache_len": cache_len,
        "peak_queue_depth": peak_queue,
        "int8": int8, "int4": int4, "kv_int8": kv_int8,
        "speculate_k": speculate_k, "donate_cache": donate,
        "kv_page_tokens": kv_stats.get("page_tokens"),
        "kv_page_bytes": kv_stats.get("page_bytes", 0),
        "kv_pages_shared": kv_stats.get("pages_shared", 0),
        "prefix_hit_rate": (round(pc_hits / (pc_hits + pc_misses), 3)
                            if pc_hits + pc_misses else None),
        "model": cfg.name, "params": cfg.param_count,
        "backend": jax.default_backend(),
    }
    if label:
        rec["label"] = label
    if speculate_k and proposed:
        rec["spec_accept_rate"] = round(accepted / proposed, 3)
    return rec


def run_spec_drift() -> int:
    """bf16 speculative greedy drift, measured (r3 VERDICT item 8).

    Greedy speculative decoding is PROVEN token-exact in f32; at bf16,
    K-wide verify and 1-wide decode reduce in different shapes, so logit
    near-ties can tie-break differently (documented as inherent in
    ROUND3_NOTES). This puts an error bar on it: same params, same greedy
    prompts, speculate_k=3 vs 0, token-level divergence rate over a
    corpus. Runs on CPU too (same-reduction-shape question exists there),
    but the deployment claim needs the chip's bf16 units — the watcher
    queues it for TPU."""
    _force_platform_from_env()
    import jax
    from k8s_runpod_kubelet_tpu.workloads.serving import (ServingConfig,
                                                          ServingEngine)

    on_tpu = jax.default_backend() == "tpu"
    model = _arg_value("--model", "bench-260m" if on_tpu else "tiny")
    cfg = _serve_model(model)
    params = _serve_params(cfg, 0)
    n_req, new_toks, prompt_len = (48, 64, 64) if on_tpu else (12, 16, 16)
    cache_len = 2048 if on_tpu else 128

    def run_greedy(spec_k: int) -> list[list[int]]:
        sc = ServingConfig(slots=8 if on_tpu else 4,
                           max_prefill_len=min(cache_len // 2, 512),
                           cache_len=cache_len, max_new_tokens=new_toks,
                           speculate_k=spec_k)
        engine = ServingEngine(cfg, params, sc).start()
        try:
            futs = []
            for i in range(n_req):
                # distinct prompt PER REQUEST (a corpus, not one prompt
                # measured n times); repeated halves so prompt-lookup
                # drafting actually fires
                base = [((i * 131 + j * 7) % 97) + 1
                        for j in range(prompt_len // 2)]
                futs.append(engine.submit(base + base, temperature=0.0,
                                          max_new_tokens=new_toks))
            return [f.result(timeout=1800)["tokens"] for f in futs]
        finally:
            engine.stop()

    plain = run_greedy(0)
    spec = run_greedy(3)
    diverged = 0
    first_div_pos = []
    tok_total = tok_same = 0
    for a, b in zip(plain, spec):
        n = min(len(a), len(b))
        tok_total += n
        same = next((i for i in range(n) if a[i] != b[i]), None)
        if same is None and len(a) == len(b):
            tok_same += n
            continue
        diverged += 1
        pos = same if same is not None else n
        first_div_pos.append(pos)
        tok_same += pos
    _emit({"metric": "spec_bf16_drift",
           "value": round(diverged / n_req, 4),
           "unit": "diverged_request_rate",
           "token_match_rate": round(tok_same / max(tok_total, 1), 4),
           "requests": n_req, "new_tokens": new_toks,
           "first_divergence_positions": sorted(first_div_pos)[:10],
           "dtype": str(cfg.dtype.__name__ if hasattr(cfg.dtype, "__name__")
                        else cfg.dtype),
           "backend": jax.default_backend(), "model": cfg.name})
    return 0


def run_serve_bench(quick: bool) -> int:
    """Serving throughput/latency under concurrent load (VERDICT r1 item 8):
    continuous batching with the prefill thread; reports tokens/sec, p50/p99
    request latency, and the HPA queue-depth signal.

    --model llama3-8b --int8 --kv-int8 is the BASELINE.md headline run
    ("tokens/sec/chip at 8B"): int8 weights (~8GB) + int8 KV fit the 16GB
    v5e chip."""
    _force_platform_from_env()
    import jax

    tiny = quick or jax.default_backend() != "tpu"
    model = _arg_value("--model", "tiny" if tiny else "bench-260m")
    big = not tiny and model not in ("tiny", "bench-260m")
    # big-model slots: decode re-reads the whole weight tree every step, so
    # tok/s scales with batch until HBM pushes back — AOT slot sweeps
    # (aot_v5e.json): int8+int8KV 16 fits (roofline 2076, +14% over 8; 32
    # OOMs at 16.42G); int4+int8KV via the Pallas kernel also fits 16
    # (decode_8b_int4pk_kv8_slots16, bound 2292). The sweeps validated
    # EXACTLY llama3-8b + {int8|int4} weights + int8 KV; other big configs
    # keep the conservative 8 (bf16 KV alone adds ~2.1GB at 16 slots)
    swept_16 = (model == "llama3-8b" and "--kv-int8" in sys.argv
                and "--int8" in sys.argv)
    # int4's smaller weights admit MORE slots: the AOT sweep compiles 32
    # (decode_8b_int4pk_kv8_slots32, bound 2,402 vs 2,292 at 16; 64 OOMs)
    swept_32 = (model == "llama3-8b" and "--kv-int8" in sys.argv
                and "--int4" in sys.argv)
    if tiny:
        slots, n_req, new_toks = 4, 12, 16
    elif big:
        slots, n_req, new_toks = ((32, 96, 64) if swept_32 else
                                  (16, 48, 64) if swept_16 else (8, 32, 64))
    else:
        slots, n_req, new_toks = 8, 48, 64
    rec = serve_once(
        model,
        slots=int(_arg_value("--slots", str(slots))),
        n_req=n_req, new_toks=new_toks,
        cache_len=int(_arg_value("--cache-len",
                                 "128" if tiny else "2048" if big else "1024")),
        prompt_len=32 if not big else 128,
        int8="--int8" in sys.argv,
        int4="--int4" in sys.argv,
        kv_int8="--kv-int8" in sys.argv,
        speculate_k=3 if "--speculate" in sys.argv else 0)
    _emit(rec)
    return 0


def run_econ_bench() -> int:
    """Serving-economics A/B matrix (VERDICT r2 item 3): measure the HBM
    claims — int8-KV on/off, cache donation on/off, speculation on/off —
    same model, same load, one JSON line per cell. Needs the chip: these
    are bandwidth effects CPU cannot show."""
    _force_platform_from_env()
    import jax

    on_tpu = jax.default_backend() == "tpu"
    model = _arg_value("--model", "bench-260m" if on_tpu else "tiny")
    kw = dict(slots=8, n_req=32, new_toks=64, cache_len=2048,
              prompt_len=64) if on_tpu else \
         dict(slots=4, n_req=8, new_toks=8, cache_len=128, prompt_len=16)
    int8 = "--int8" in sys.argv
    cells = [
        ("baseline", dict(int8=int8, kv_int8=False, speculate_k=0,
                          donate=True)),
        ("kv_int8", dict(int8=int8, kv_int8=True, speculate_k=0,
                         donate=True)),
        ("no_donation", dict(int8=int8, kv_int8=False, speculate_k=0,
                             donate=False)),
        ("speculate3", dict(int8=int8, kv_int8=False, speculate_k=3,
                            donate=True)),
        ("kv_int8+speculate3", dict(int8=int8, kv_int8=True, speculate_k=3,
                                    donate=True)),
    ]
    # one param tree for the whole matrix (int8 is constant across cells);
    # per-cell engines/caches/jits still rebuild, which is what's measured
    cfg = _serve_model(model)
    params = _serve_params(cfg, 8 if int8 else 0)
    base_val = None
    for label, flags in cells:
        try:
            rec = serve_once(model, label=label, params=params, **kw, **flags)
        except Exception as e:  # noqa: BLE001 — e.g. no_donation OOM: the
            # failing cell IS a result; the rest of the matrix must run
            rec = {"metric": "serving_tokens_per_sec", "value": None,
                   "label": label, "error": f"{type(e).__name__}: {e}"[:300]}
            _emit(rec)
            continue
        if label == "baseline":
            base_val = rec["value"]
        elif base_val:
            rec["vs_econ_baseline"] = round(rec["value"] / base_val, 3)
        _emit(rec)
    return 0


def run_attn_tune() -> int:
    """Flash block-size tuner at the TRAINING bench geometry (S=2048,
    hd=64 — the remaining queued MFU lever from ROUND2_NOTES): times the
    fwd+bwd kernel over a (block_q, block_k) grid and prints the winner
    vs the tuned_block_sizes default. Persist a better pick into
    ops/attention.py's _BLOCK_CAPS table if one shows up."""
    _force_platform_from_env()
    import jax
    import jax.numpy as jnp
    from k8s_runpod_kubelet_tpu.ops.attention import (flash_attention,
                                                      tuned_block_sizes)

    if jax.default_backend() != "tpu":
        _emit({"metric": "attn_tune", "value": None,
               "error": "tuner needs the TPU"})
        return 1
    # bench-260m attention geometry: B=8, Hq=16, Hkv=8, S=2048, D=64
    b, hq, hkv, s, d = 8, 16, 8, 2048, 64
    ks = jax.random.split(jax.random.PRNGKey(0), 4)
    q = jax.random.normal(ks[0], (b, hq, s, d), jnp.bfloat16)
    k = jax.random.normal(ks[1], (b, hkv, s, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, hkv, s, d), jnp.bfloat16)
    g = jax.random.normal(ks[3], (b, hq, s, d), jnp.bfloat16)

    def timed(bq, bk):
        def run(q, k, v):
            out, pull = jax.vjp(
                lambda q, k, v: flash_attention(
                    q, k, v, causal=True, use_pallas=True,
                    block_q=bq, block_k=bk), q, k, v)
            return pull(g)
        fn = jax.jit(run)
        jax.tree_util.tree_leaves(fn(q, k, v))[0].block_until_ready()
        t0 = time.perf_counter()
        for _ in range(20):
            out = fn(q, k, v)
        jax.tree_util.tree_leaves(out)[0].block_until_ready()
        return (time.perf_counter() - t0) / 20

    default = tuned_block_sizes(s, s)
    grid = [(bq, bk) for bq in (128, 256, 512) for bk in (128, 256, 512, 1024)]
    best = None
    for bq, bk in grid:
        try:
            t = timed(bq, bk)
        except Exception as e:  # noqa: BLE001 — VMEM overflow etc.
            _emit({"metric": f"attn_tune_q{bq}_k{bk}", "value": None,
                   "error": f"{type(e).__name__}"[:80]})
            continue
        rec = {"metric": f"attn_tune_q{bq}_k{bk}", "unit": "ms",
               "value": round(t * 1e3, 3),
               "is_default": [bq, bk] == list(default)}
        _emit(rec)
        if best is None or t < best[0]:
            best = (t, bq, bk)
    if best:
        _emit({"metric": "attn_tune_best", "unit": "ms",
               "value": round(best[0] * 1e3, 3),
               "blocks": [best[1], best[2]], "default": list(default)})
    return 0


def run_mfu_sweep() -> int:
    """Training MFU sweep (VERDICT r2 item 1): the queued levers from
    ROUND2_NOTES, one JSON line per point, best-first summary at the end.
    Levers: remat policy (none frees an extra fwd pass — the 260M model has
    HBM headroom), global batch, a wider 530M model, and flash block sizes.
    Run on the chip; each point is ~2 min including compile."""
    _force_platform_from_env()
    import dataclasses
    import jax
    from __graft_entry__ import _bench_config, _bench_config_530m
    from k8s_runpod_kubelet_tpu.workloads.train import (TrainConfig, Trainer,
                                                        synthetic_batches)

    if jax.default_backend() != "tpu":
        _emit({"metric": "mfu_sweep", "value": None,
               "error": "sweep needs the TPU"})
        return 1
    gen = detect_generation()
    peak = _PEAK_TFLOPS[gen]
    wider_530m = _bench_config_530m

    base = _bench_config(tiny=False)
    # Grid AOT-prevalidated against the v5e memory model (tools/aot_check.py,
    # bench_results/aot_v5e.json): remat "none" OOMs at any batch (24GB at
    # B=8), 530m "dots" OOMs at B=8 (18.9GB), and dots_b12 compiles but
    # peaks at an estimated 21GB — XLA's buffer assignment for the v5e
    # target, so they'd OOM on the chip too. What fits: dots_b8 (15.6GB),
    # full_b16 (12.6GB; "full" recomputes activations, buying batch — its
    # XLA roofline bound is 20% above dots_b8's), 530m_full_b8 (14.4GB).
    # full_b20 interpolates toward full_b32's refusal point (18.2GB).
    from __graft_entry__ import _bench_config_v128k
    points = [
        ("260m_dots_b8", base, 8, 0),                    # r2 best: MFU .318
        ("260m_full_b16",
         dataclasses.replace(base, remat_policy="full"), 16, 0),
        ("260m_full_b20",
         dataclasses.replace(base, remat_policy="full"), 20, 0),
        ("530m_full_b8",
         dataclasses.replace(wider_530m(), remat_policy="full"), 8, 0),
        # fused chunked CE (ops/fused_ce.py): logits never materialize.
        # Fit criterion: these cells COMPILED under the v5e compiler's
        # 15.75G buffer-assignment budget (aot_v5e.json train_260m_fce8_*,
        # compile_ok) — the authoritative check; the JSON's fits_16gb
        # estimator double-counts donated/scan buffers and flags them
        # false. XLA cost-model rooflines are NOT comparable across these
        # cells either (scan bodies counted once) — chip wall-clock decides.
        ("260m_fce8_dots_b8", base, 8, 8),
        ("260m_fce8_full_b24",
         dataclasses.replace(base, remat_policy="full"), 24, 8),
        ("530m_fce8_full_b12",
         dataclasses.replace(wider_530m(), remat_policy="full"), 12, 8),
        # Llama-3's real 128k vocab: the naive loss refuses at B=8 on v5e
        # (4.2GB bf16 logits); fused is the only way to run this geometry
        ("v128k_fce16_b8", _bench_config_v128k(), 8, 16),
    ]
    results = []
    for label, cfg, batch, fce in points:
        trainer = None
        try:
            tc = TrainConfig(batch_size=batch, seq_len=2048, steps=20,
                             warmup_steps=1, fused_ce_chunks=fce)
            trainer = Trainer(cfg, tc)
            batches = synthetic_batches(cfg, tc)
            trainer.run(steps=3, batches=batches)       # compile + warm
            t0 = time.perf_counter()
            trainer.run(steps=10, batches=batches)
            wall = time.perf_counter() - t0
            tok_s = batch * 2048 * 10 / wall
            mfu = 6.0 * cfg.param_count * tok_s / (peak * 1e12)
            rec = {"metric": f"mfu_{label}", "value": round(tok_s, 1),
                   "unit": "tok/s/chip", "mfu": round(mfu, 3),
                   "params": cfg.param_count, "global_batch": batch,
                   "remat": cfg.remat_policy}
        except Exception as e:  # noqa: BLE001 — OOM etc: report, keep going
            rec = {"metric": f"mfu_{label}", "value": None,
                   "error": f"{type(e).__name__}: {e}"[:300]}
        finally:
            trainer = None  # release params+opt state HBM before next point
        results.append(rec)
        _emit(rec)
        jax.clear_caches()
    best = max((r for r in results if r.get("value")),
               key=lambda r: r["mfu"], default=None)
    if best:
        _emit({"metric": "mfu_sweep_best", "value": best["mfu"],
               "unit": "mfu", "point": best["metric"],
               "vs_baseline": round(best["mfu"] / _TARGET_MFU, 3)})
    return 0


# --------------------------------------------------------------------------
# parent: orchestrator (imports no jax; always emits one JSON line)
# --------------------------------------------------------------------------

def _last_json_line(text: str) -> dict | None:
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def _probe_tpu() -> tuple[bool, str]:
    """Can a child process initialize the TPU backend AND run one tiny
    computation on it? Bounded by _PROBE_TIMEOUT_S so a hung tunnel costs
    minutes, not attempt-timeouts. The compute check matters: a half-up
    tunnel can enumerate devices fine while the compile/execute channel is
    dead (observed r4: headline died 26 min in with 'UNAVAILABLE: TPU
    backend setup/compile error' after a clean init probe) — device init
    alone would keep reporting UP and feed every staged step to the same
    slow death. Returns (ok, diagnostic)."""
    code = ("import jax, sys; "
            "sys.exit(1) if jax.default_backend() != 'tpu' else None; "
            "import jax.numpy as jnp; "
            "v = int(jax.jit(lambda x: (x + 1).sum())(jnp.zeros((8, 8)))); "
            "sys.exit(0 if v == 64 else 1)")
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=_PROBE_TIMEOUT_S)
        if proc.returncode == 0:
            return True, ""
        return False, (proc.stderr or "")[-400:]
    except subprocess.TimeoutExpired:
        return False, f"probe hung > {_PROBE_TIMEOUT_S}s (tunnel wedged?)"
    except Exception as e:  # noqa: BLE001 - spawn failure
        return False, f"{type(e).__name__}: {e}"


def _run_child(quick: bool, platform: str | None, timeout_s: int):
    """Returns (parsed_json_or_None, rc, tail)."""
    env = dict(os.environ)
    cmd = [sys.executable, os.path.abspath(__file__), "--run"]
    if platform is not None:
        env["JAX_PLATFORMS"] = platform
    else:
        cmd.append("--expect-tpu")  # fail fast if jax falls back to CPU
    if quick:
        cmd.append("--quick")
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout_s, env=env, cwd=_HERE)
        out = proc.stdout or ""
        parsed = _last_json_line(out)
        tail = ((proc.stderr or "")[-800:]) if parsed is None else ""
        return parsed, proc.returncode, tail
    except subprocess.TimeoutExpired as e:
        partial = e.stderr or b""
        if isinstance(partial, bytes):
            partial = partial.decode(errors="replace")
        return None, -1, f"timeout after {timeout_s}s; stderr tail: {partial[-800:]}"
    except Exception as e:  # noqa: BLE001
        return None, -2, f"{type(e).__name__}: {e}"


def _git_commit() -> str:
    try:
        out = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                             capture_output=True, text=True, cwd=_HERE,
                             timeout=10)
        return out.stdout.strip() or "unknown"
    except Exception:  # noqa: BLE001
        return "unknown"


def _result_path(name: str) -> str:
    return os.path.join(_RESULTS_DIR, f"{name}.json")


def _load_result(name: str) -> dict | None:
    try:
        with open(_result_path(name), encoding="utf-8") as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def _run_staged_step(name: str, argv: list[str], timeout_s: int) -> dict:
    """Run one runbook step in a child process; persist EVERY JSON line it
    emits (some benches emit several) plus enough context to audit later."""
    cmd = [sys.executable, os.path.abspath(__file__)] + argv
    rec = {"name": name, "argv": argv,
           "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
           "commit": _git_commit()}
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout_s, cwd=_HERE)
        lines = []
        for line in (proc.stdout or "").splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    lines.append(json.loads(line))
                except json.JSONDecodeError:
                    pass
        rec.update(rc=proc.returncode, lines=lines,
                   stderr_tail=(proc.stderr or "")[-800:],
                   ok=proc.returncode == 0 and bool(lines))
    except subprocess.TimeoutExpired as e:
        partial = e.stderr or b""
        if isinstance(partial, bytes):
            partial = partial.decode(errors="replace")
        rec.update(rc=-1, lines=[],
                   stderr_tail=(f"timeout after {timeout_s}s; stderr tail: "
                                f"{partial[-700:]}"),
                   ok=False)
    except Exception as e:  # noqa: BLE001
        rec.update(rc=-2, lines=[], stderr_tail=f"{type(e).__name__}: {e}",
                   ok=False)
    os.makedirs(_RESULTS_DIR, exist_ok=True)
    tmp = _result_path(name) + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(rec, f, indent=1)
        f.write("\n")
    os.replace(tmp, _result_path(name))
    if rec.get("ok"):  # cross-session record store (VERDICT r4 item 1b)
        for line in rec.get("lines", []):
            if line.get("metric") == "train_tokens_per_sec_per_chip":
                _append_tpu_record(line, source=f"watcher:{name}")
    return rec


def _result_age_s(rec: dict) -> float:
    """Age of a persisted result record, +inf if unparseable."""
    try:
        import calendar
        ts = calendar.timegm(time.strptime(rec["ts"], "%Y-%m-%dT%H:%M:%SZ"))
        return max(0.0, time.time() - ts)
    except (KeyError, ValueError, TypeError):
        return float("inf")


# --------------------------------------------------------------------------
# cross-session TPU record store (VERDICT r4 item 1b): every successful
# on-chip headline is appended to an immutable jsonl with provenance; the
# driver-time orchestrator falls back across SESSIONS to the freshest one
# (clearly stamped stale) instead of emitting a meaningless CPU line.
# --------------------------------------------------------------------------

def _tpu_records_path() -> str:
    return os.path.join(_RESULTS_DIR, "tpu_records.jsonl")


def _append_tpu_record(line: dict, source: str) -> None:
    """Persist a measured on-chip headline. Only real TPU numbers qualify."""
    if line.get("value") is None or line.get("generation") in (None, "cpu"):
        return
    rec = {"ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
           "commit": _git_commit(), "source": source, "line": dict(line)}
    try:
        os.makedirs(_RESULTS_DIR, exist_ok=True)
        with open(_tpu_records_path(), "a", encoding="utf-8") as f:
            f.write(json.dumps(rec) + "\n")
    except OSError as e:
        print(f"[bench] tpu_records append failed: {e}", file=sys.stderr)


def _rec_ts(rec: dict) -> float:
    """Epoch seconds of a record's ts, -inf if unparseable."""
    try:
        import calendar
        return float(calendar.timegm(
            time.strptime(rec["ts"], "%Y-%m-%dT%H:%M:%SZ")))
    except (KeyError, ValueError, TypeError):
        return float("-inf")


def _best_known_record() -> dict | None:
    """Freshest entry in the record store, any age — staleness is stamped,
    not filtered: a months-old on-chip measurement beats a CPU number of a
    TPU framework every time (VERDICT r4 weak item 1)."""
    best = None
    try:
        with open(_tpu_records_path(), encoding="utf-8") as f:
            for raw in f:
                raw = raw.strip()
                if not raw:
                    continue
                try:
                    rec = json.loads(raw)
                except json.JSONDecodeError:
                    continue
                line = rec.get("line") or {}
                if (line.get("value") is None
                        or line.get("generation") in (None, "cpu")):
                    continue
                # compare parsed timestamps directly (>= : same-second ties
                # go to the later file entry), never two time.time() samples
                if best is None or _rec_ts(rec) >= _rec_ts(best):
                    best = rec
    except OSError:
        return None
    return best


def _probe_diag_summary() -> dict | None:
    """Per-variant wedge stages from the latest tools/probe_diag.py run, so
    a fallback BENCH line carries the diagnosis, not just 'probe failed'."""
    try:
        with open(os.path.join(_RESULTS_DIR, "probe_diag.json"),
                  encoding="utf-8") as f:
            report = json.load(f)
        return {"ts": report.get("ts"),
                "variants": {v["variant"]: (v.get("wedged_stage")
                                            or ("ok" if v.get("ok")
                                                else "error"))
                             for v in report.get("variants", [])}}
    except (OSError, json.JSONDecodeError, KeyError, TypeError):
        return None


def _run_probe_diag(deadline: float):
    """Spawn tools/probe_diag.py (bounded by the watch deadline) and return
    its per-variant wedge summary. Separate function so tests mock it — a
    real spawn under pytest's CPU env once clobbered the genuine tunnel
    diagnosis with an all-cpu false pass."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(_HERE, "tools", "probe_diag.py")],
            capture_output=True, text=True,
            timeout=min(3000, max(60, int(deadline - time.monotonic()))),
            cwd=_HERE)
        summ = _last_json_line(proc.stdout or "")
        return (summ or {}).get("variants")
    except Exception as e:  # noqa: BLE001 — diag must never kill the watch
        return f"diag failed: {type(e).__name__}: {e}"


def run_watch() -> int:
    """Session watcher: probe the TPU on an interval for up to the budget; on
    the first success run the staged runbook, persisting each step's JSON as
    it lands so a short tunnel window mid-session still yields the round's
    numbers. Steps with a RECENT ok persisted result (younger than
    --max-age-s, default 8h ~ one build session) are skipped, so the watcher
    is restartable and a tunnel that flaps mid-queue resumes where it left
    off — while a new session never silently trusts a previous round's
    numbers. Pass --fresh to rerun everything. A step that keeps failing
    while the tunnel is UP (a real bug, not a flap) is retried at most
    _STEP_MAX_ATTEMPTS times, with an interval sleep between queue passes so
    a deterministic failure can't spin the whole budget away."""
    budget = int(_arg_value("--budget-s", os.environ.get(
        "BENCH_WATCH_BUDGET_S", str(_WATCH_BUDGET_S))))
    interval = int(_arg_value("--interval-s", str(_WATCH_INTERVAL_S)))
    max_age = float(_arg_value("--max-age-s", str(8 * 3600)))
    # --fresh: every step must rerun THIS invocation regardless of prior
    # results — tracked as a per-step set (not a flag cleared at the first
    # window) so a flap mid-queue can't silently demote the rest of the
    # queue back to resume semantics
    force = ({name for name, _, _ in _STAGED_QUEUE}
             if "--fresh" in sys.argv else set())
    deadline = time.monotonic() + budget
    attempts: dict[str, int] = {}

    def log(msg: str) -> None:
        print(f"[watch {time.strftime('%H:%M:%S')}] {msg}",
              file=sys.stderr, flush=True)

    def pending() -> list[tuple[str, list[str], int]]:
        out = []
        for name, argv, t in _STAGED_QUEUE:
            if attempts.get(name, 0) >= _STEP_MAX_ATTEMPTS:
                continue  # given up; recorded below
            prior = None if name in force else _load_result(name)
            if (prior is None or not prior.get("ok")
                    or _result_age_s(prior) > max_age):
                out.append((name, argv, t))
        return out

    gave_up: list[str] = []
    last_diag = float("-inf")  # diag cadence (VERDICT r4 item 1c); -inf so
    # the FIRST failed probe always diagnoses (monotonic() is uptime — 0.0
    # would suppress the diag on a freshly booted machine)
    while time.monotonic() < deadline:
        todo = pending()
        if not todo:
            log("all staged steps have recent ok results; done"
                + (f" (gave up on: {gave_up})" if gave_up else ""))
            return 0 if not gave_up else 1
        ok, diag = _probe_tpu()
        if not ok:
            log(f"probe failed ({diag[:120]}); {len(todo)} steps pending; "
                f"sleeping {interval}s")
            if time.monotonic() - last_diag > 7200:
                last_diag = time.monotonic()
                log("running probe-stage diagnosis (tools/probe_diag.py)")
                log(f"diag: {json.dumps(_run_probe_diag(deadline))}")
            time.sleep(min(interval, max(0, deadline - time.monotonic())))
            continue
        log(f"TPU is UP — running {len(todo)} staged steps")
        any_failed_with_tpu_up = False
        for name, argv, t in todo:
            log(f"step {name}: {' '.join(argv)}")
            rec = _run_staged_step(name, argv, t)
            log(f"step {name}: ok={rec['ok']} rc={rec['rc']} "
                f"lines={len(rec['lines'])}")
            if rec["ok"]:
                attempts[name] = 0  # only count consecutive failures
                force.discard(name)  # --fresh satisfied for this step
                continue
            # hang or error mid-queue: if the tunnel died this was a FLAP,
            # not the step's fault — don't count it; go back to waiting
            # (the step stays pending and reruns next window)
            ok2, diag2 = _probe_tpu()
            if not ok2:
                log(f"tunnel died mid-queue ({diag2[:120]}); waiting")
                break
            attempts[name] = attempts.get(name, 0) + 1
            if attempts[name] >= _STEP_MAX_ATTEMPTS:
                gave_up.append(name)
                log(f"step {name}: giving up after {attempts[name]} "
                    f"attempts with a healthy tunnel")
            any_failed_with_tpu_up = True
        if any_failed_with_tpu_up:
            # deterministic failure, tunnel healthy: don't re-spin instantly
            time.sleep(min(interval, max(0, deadline - time.monotonic())))
    left = [n for n, _, _ in pending()]
    if left or gave_up:
        log(f"budget exhausted; pending={left} gave_up={gave_up}")
        return 1
    return 0


def _session_tpu_headline() -> dict | None:
    """Persisted TPU headline from the session watcher, if recent enough.
    Bounded by _SESSION_MAX_AGE_S (default 24h) so a weeks-old number can
    never masquerade as this run's result; the emitted line carries
    measured_ts + measured_commit for audit either way."""
    rec = _load_result("headline")
    if not rec or not rec.get("ok"):
        return None
    if _result_age_s(rec) > _SESSION_MAX_AGE_S:
        return None
    for line in reversed(rec.get("lines", [])):
        if (line.get("metric") == "train_tokens_per_sec_per_chip"
                and line.get("value") is not None
                and line.get("generation") not in (None, "cpu")):
            line = dict(line)
            line["source"] = "session_watcher"
            line["measured_ts"] = rec.get("ts")
            line["measured_commit"] = rec.get("commit")
            return line
    return None


_BENCH_ROUND_RE = None  # compiled lazily (re import stays out of hot path)
_ROUND_DIR = _HERE      # where BENCH_r<NN>.json rounds live (tests patch)


def _write_unreachable_round(line: dict, root: str | None = None) -> str | None:
    """The TPU didn't answer this round: write an EXPLICIT ``unreachable``
    row into a fresh BENCH_r<NN>.json (NN = newest existing + 1) instead of
    silently leaving the trajectory stale on the last measured round
    (ROADMAP cross-cutting note: BENCH_r05 served stale single-chip numbers
    for two rounds because the wedged tunnel only surfaced in stderr).
    Repeated wedged runs AT THE SAME COMMIT overwrite the same unreachable
    round rather than minting a new file each time; a new commit is a new
    round — each PR's trajectory entry stays its own file even when the
    tunnel never heals. Returns the path written, or None."""
    global _BENCH_ROUND_RE
    import re as _re
    if _BENCH_ROUND_RE is None:
        _BENCH_ROUND_RE = _re.compile(r"^BENCH_r(\d+)\.json$")
    root = root if root is not None else _ROUND_DIR
    rounds = []
    try:
        for name in os.listdir(root):
            m = _BENCH_ROUND_RE.match(name)
            if m:
                rounds.append((int(m.group(1)), name))
    except OSError:
        return None
    if not rounds:
        return None  # no trajectory to keep fresh (new checkout)
    newest_n, newest_name = max(rounds)
    n = newest_n + 1
    try:  # overwrite our own unreachable marker instead of proliferating
        with open(os.path.join(root, newest_name), encoding="utf-8") as f:
            newest = json.load(f)
        if (newest.get("parsed") or {}).get("unreachable") \
                and newest.get("commit") in (None, _git_commit()):
            n = newest_n
    except (OSError, json.JSONDecodeError):
        pass
    path = os.path.join(root, f"BENCH_r{n:02d}.json")
    rec = {"n": n, "cmd": "bench.py orchestrator (TPU probe gate)",
           "rc": 1, "tail": "", "parsed": line,
           "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
           "commit": _git_commit()}
    try:
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(rec, f, indent=1)
            f.write("\n")
        os.replace(tmp, path)
    except OSError as e:
        print(f"[bench] could not write {path}: {e}", file=sys.stderr)
        return None
    print(f"[bench] TPU unreachable — wrote explicit row to {path}",
          file=sys.stderr, flush=True)
    return path


def _cpu_smoke_lines(flag: str, timeout_s: int = 300) -> list | None:
    """One bench cell on CPU, in a subprocess (the orchestrator process
    stays jax-free): an unreachable round still records REAL measured
    numbers — explicitly backend=cpu, never a chip claim — next to the
    loud `unreachable` flag."""
    try:
        out = subprocess.run(
            [sys.executable, os.path.join(_HERE, "bench.py"),
             flag, "--smoke"],
            capture_output=True, text=True, timeout=timeout_s,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
    except Exception:  # noqa: BLE001 — the smoke must never sink the round
        return None
    lines = []
    for ln in out.stdout.splitlines():
        try:
            obj = json.loads(ln)
        except json.JSONDecodeError:
            continue
        if isinstance(obj, dict) and obj.get("metric"):
            lines.append(obj)
    return lines or None


def _disagg_smoke_lines() -> list | None:
    """The ISSUE 9 handoff cell on CPU (see _cpu_smoke_lines)."""
    return _cpu_smoke_lines("--disagg")


def _chunked_smoke_lines() -> list | None:
    """The ISSUE 10 chunked-prefill cells on CPU (see _cpu_smoke_lines):
    the streamed-vs-serial two-hop TTFT sweep + the ITL-under-prefill
    contrast ride every unreachable round, so the overlap claim is
    re-measured per commit even with the chip away."""
    return _cpu_smoke_lines("--chunked", timeout_s=900)


def _handoff_path_smoke_lines() -> list | None:
    """The ISSUE 11 device-vs-wire throughput cell on CPU (see
    _cpu_smoke_lines): the device/wire ratio is re-measured per commit —
    tiny geometry, explicitly backend=cpu, but the mechanism (zero
    serialization on the device leg) is the same one the chip runs."""
    return _cpu_smoke_lines("--handoff-path")


def _kv_fabric_smoke_lines() -> list | None:
    """The ISSUE 16 directory-pull cell on CPU (see _cpu_smoke_lines):
    per-rung pull TTFT vs cold re-prefill through the real /kv_fetch
    ladder — tiny geometry, but the mechanism (match-only export, shm
    blob transport, downgrade discipline) is the one the chip runs."""
    return _cpu_smoke_lines("--kv-fabric", timeout_s=900)


def _flight_recorder_smoke_lines() -> list | None:
    """The ISSUE 17 flight-recorder cell on CPU (see _cpu_smoke_lines):
    recorder overhead + step-phase medians + the watchdog's recompile
    count re-measured per commit — the round that records the phase
    numbers was itself produced with the recorder on, so BENCH_r13-class
    rows are self-reporting."""
    return _cpu_smoke_lines("--flight-recorder", timeout_s=900)


def _scheduler_smoke_lines() -> list | None:
    """The ISSUE 19 fleet-scheduler cell (see _cpu_smoke_lines): hetero
    vs round-robin goodput-per-dollar over the deterministic fake cloud.
    Pure control plane — it never dials the chip, so the placement win
    is re-measured per commit on every unreachable round."""
    return _cpu_smoke_lines("--scheduler")


def _cost_smoke_lines() -> list | None:
    """The ISSUE 20 cost-attribution cell on CPU (see _cpu_smoke_lines):
    the telescope identity, meter overhead and saturated-arm idle burn
    re-measured per commit — the mechanism (boundary clamp, one fold per
    completed request) is the one the chip runs; only the $/Mtok
    headline waits on the tunnel."""
    return _cpu_smoke_lines("--cost", timeout_s=900)


def _paged_tp_smoke_lines() -> list | None:
    """The ISSUE 12 TP paged-decode cell on CPU (see _cpu_smoke_lines):
    paged-vs-contiguous mesh decode step time at tp=2 over virtual
    devices — the shard_map/GSPMD overhead contrast is re-measured per
    commit; the per-chip chip claim waits on the tunnel."""
    return _cpu_smoke_lines("--paged-attn", timeout_s=900)


def orchestrate(quick: bool) -> int:
    errors = []
    # 0) a bounded probe gates the expensive attempts: a probe pass costs one
    # init; a probe fail saves 3 x 1500s of guaranteed hangs. The probe
    # itself retries (r3 VERDICT: one instant's probe can miss a flapping
    # tunnel's window) — bounded so the driver's own deadline survives.
    retries = int(os.environ.get("BENCH_PROBE_RETRIES", "2"))
    ok, diag = False, ""
    for i in range(max(1, retries)):
        ok, diag = _probe_tpu()
        if ok:
            break
        if i + 1 < max(1, retries):
            time.sleep(60)
    attempts = _TPU_ATTEMPTS if ok else 0
    if not ok:
        errors.append(f"tpu probe: {diag}")
        print(f"[bench] TPU probe failed: {diag}", file=sys.stderr, flush=True)
    # 1) TPU (default platform) with retries — the tunnel can be slow.
    for attempt in range(1, attempts + 1):
        parsed, rc, tail = _run_child(quick, platform=None,
                                      timeout_s=_TPU_TIMEOUT_S)
        if parsed is not None and parsed.get("value") is not None:
            if not quick:  # tiny-config numbers must never become the
                _append_tpu_record(parsed, source="orchestrator_live")
            _emit(parsed)  # best-known HEADLINE record
            return 0
        err = (parsed or {}).get("error") or tail or f"rc={rc}"
        errors.append(f"tpu[{attempt}]: {err}")
        print(f"[bench] TPU attempt {attempt}/{_TPU_ATTEMPTS} failed: {err}",
              file=sys.stderr, flush=True)
        if attempt < attempts:
            time.sleep(_TPU_RETRY_SLEEP_S)

    # 2) No live TPU — prefer a real TPU number persisted by the session
    # watcher over a meaningless CPU line (r3 VERDICT weak item 2). Either
    # way the round is marked `unreachable` LOUDLY: the emitted line carries
    # the flag and a fresh BENCH_r<NN>.json records it, so a wedged tunnel
    # can never leave the perf trajectory silently stale (this is how two
    # rounds quietly re-served the r02 measurement).
    diag = _probe_diag_summary()
    smoke = None if quick else _disagg_smoke_lines()
    chunked_smoke = None if quick else _chunked_smoke_lines()
    handoff_smoke = None if quick else _handoff_path_smoke_lines()
    kv_fabric_smoke = None if quick else _kv_fabric_smoke_lines()
    fr_smoke = None if quick else _flight_recorder_smoke_lines()
    paged_tp_smoke = None if quick else _paged_tp_smoke_lines()
    scheduler_smoke = None if quick else _scheduler_smoke_lines()
    cost_smoke = None if quick else _cost_smoke_lines()
    session = _session_tpu_headline()
    if session is not None:
        session["tpu_errors"] = errors[-2:]
        session["unreachable"] = True
        if diag is not None:
            session["probe_diag"] = diag
        if smoke is not None:
            session["disagg_cpu_smoke"] = smoke
        if chunked_smoke is not None:
            session["chunked_cpu_smoke"] = chunked_smoke
        if handoff_smoke is not None:
            session["handoff_path_cpu_smoke"] = handoff_smoke
        if kv_fabric_smoke is not None:
            session["kv_fabric_cpu_smoke"] = kv_fabric_smoke
        if fr_smoke is not None:
            session["flight_recorder_cpu_smoke"] = fr_smoke
        if paged_tp_smoke is not None:
            session["paged_tp_cpu_smoke"] = paged_tp_smoke
        if scheduler_smoke is not None:
            session["scheduler_cpu_smoke"] = scheduler_smoke
        if cost_smoke is not None:
            session["cost_cpu_smoke"] = cost_smoke
        if not quick:
            _write_unreachable_round(session)
        _emit(session)
        return 0

    # 2b) Cross-session fallback (r4 VERDICT item 1b): the freshest on-chip
    # record the project owns, stamped stale with full provenance. A TPU
    # framework's bench must never claim a CPU number while a real chip
    # measurement exists.
    best = _best_known_record()
    if best is not None:
        line = dict(best["line"])
        line.update(source="best_known_record", stale=True, unreachable=True,
                    measured_ts=best.get("ts"),
                    measured_commit=best.get("commit"),
                    measured_source=best.get("source"),
                    age_h=round(_result_age_s(best) / 3600, 1),
                    tpu_errors=errors[-2:])
        if diag is not None:
            line["probe_diag"] = diag
        if smoke is not None:
            line["disagg_cpu_smoke"] = smoke
        if chunked_smoke is not None:
            line["chunked_cpu_smoke"] = chunked_smoke
        if handoff_smoke is not None:
            line["handoff_path_cpu_smoke"] = handoff_smoke
        if kv_fabric_smoke is not None:
            line["kv_fabric_cpu_smoke"] = kv_fabric_smoke
        if fr_smoke is not None:
            line["flight_recorder_cpu_smoke"] = fr_smoke
        if paged_tp_smoke is not None:
            line["paged_tp_cpu_smoke"] = paged_tp_smoke
        if scheduler_smoke is not None:
            line["scheduler_cpu_smoke"] = scheduler_smoke
        if cost_smoke is not None:
            line["cost_cpu_smoke"] = cost_smoke
        if not quick:
            _write_unreachable_round(line)
        _emit(line)
        return 0

    # 3) CPU fallback: quick config so it finishes in seconds-to-minutes.
    # Only reachable if the record store is empty — i.e. no chip has EVER
    # answered for this repo.
    parsed, rc, tail = _run_child(quick=True, platform="cpu",
                                  timeout_s=_CPU_TIMEOUT_S)
    if parsed is not None and parsed.get("value") is not None:
        parsed["fallback"] = "cpu"
        parsed["unreachable"] = True
        parsed["tpu_errors"] = errors[-2:]
        if diag is not None:
            parsed["probe_diag"] = diag
        if not quick:
            _write_unreachable_round(parsed)
        _emit(parsed)
        return 0

    errors.append(f"cpu: {(parsed or {}).get('error') or tail or f'rc={rc}'}")
    _emit({"metric": "train_tokens_per_sec_per_chip", "value": None,
           "unit": "tok/s/chip", "vs_baseline": None,
           "error": "; ".join(errors)[:1500]})
    return 1


def run_scheduler_bench(smoke: bool = False) -> int:
    """Heterogeneous fleet-scheduler cell (ISSUE 19): goodput-per-dollar
    (hetero) placement vs round-robin over a deterministic fake cloud of
    mixed TPU generations, on IDENTICAL seeded traffic. Pure control
    plane — no jax import, no chip: the placement matrix is seeded from
    the generations.py rooflines and refined online from the same
    scripted heartbeats both policies see, so the hetero-vs-RR ratio is
    re-measured per commit even while the tunnel is wedged.

    Shared trace: a serving fleet ramps decode 2->8 and prefill 1->3
    replicas (8 chips each); three best-effort 16-chip training gangs
    pack onto idle capacity at t=H/4; a guaranteed 32-chip gang arrives
    at t=H/2 into a near-full fleet and must preempt (lowest
    unsaved-work loss first). Goodput integrates FleetScheduler.rates() — the
    scheduler's own objective — and serving tokens/$ integrates the
    scripted token streams, so the headline is measured twice."""
    import types as _types

    from k8s_runpod_kubelet_tpu.fleet.scheduler import (DECODE, HETERO,
                                                        PREFILL,
                                                        ROUND_ROBIN,
                                                        TRAINING,
                                                        FleetScheduler)

    pools = "v5e:64,v5p:64,v6e:32"
    horizon_s = 120 if smoke else 600
    # scripted tokens/sec-per-chip the fake replicas report, keyed by
    # (kind, generation): decode is bandwidth-bound (v5e punches above
    # its price), prefill flops-bound (v6e/v5p). Tuple keys on purpose —
    # per-generation NUMBER tables live in generations.py only
    # (tests/test_generations.py scans for drifting copies).
    tok_rate = {(DECODE, "v5e"): 48.0, (DECODE, "v5p"): 96.0,
                (DECODE, "v6e"): 96.0,
                (PREFILL, "v5e"): 30.0, (PREFILL, "v5p"): 70.0,
                (PREFILL, "v6e"): 140.0}

    def drive(policy: str) -> dict:
        t = [0.0]
        preempted: list[str] = []
        sched = FleetScheduler(pools, clock=lambda: t[0], policy=policy,
                               preempt_fn=lambda p: preempted.append(p.tag),
                               default_serving_chips=8)
        tokens: dict[str, float] = {}
        be_placed_at: dict[str, float] = {}
        gang = None
        goodput = dollars = serve_tokens = serve_dollars = 0.0
        for step in range(horizon_s):
            t[0] = float(step)
            # serving ramp (identical under both policies)
            n_dec = min(8, 2 + (8 * step) // horizon_s)
            n_pre = min(3, 1 + (3 * step) // horizon_s)
            for i in range(n_dec):
                sched.place(DECODE, 8, f"dec-{i}")
            for i in range(n_pre):
                sched.place(PREFILL, 8, f"pre-{i}")
            if step == horizon_s // 4:       # best-effort packing
                for i in range(3):
                    if sched.place(TRAINING, 16, f"be-{i}",
                                   best_effort=True) is not None:
                        be_placed_at[f"be-{i}"] = t[0]
            if step == horizon_s // 2:       # guaranteed gang arrives
                gang = sched.place(TRAINING, 32, "gang-prod")
            # heartbeats: cumulative token counters at the scripted rate
            # of whatever generation the placement actually landed on
            for p in sched.placements():
                if p.kind not in (DECODE, PREFILL):
                    if p.tag in be_placed_at:   # telemetry scrape
                        sched.observe_training(
                            p.tag, mfu=0.35, goodput=0.9,
                            unsaved_work_s=t[0] - be_placed_at[p.tag])
                    continue
                rate = tok_rate[(p.kind, p.generation)] * p.chips
                tokens[p.tag] = tokens.get(p.tag, 0.0) + rate
                sched.observe_serving(
                    p.tag, p.kind, p.generation,
                    _types.SimpleNamespace(tokens_total=int(tokens[p.tag])))
                serve_tokens += rate
                serve_dollars += (p.chips / 3600.0
                                  * sched.pools[p.pool].spec.cost_per_chip_hr)
            g, c = sched.rates()
            goodput += g             # effective-throughput-seconds
            dollars += c / 3600.0    # $/hr integrated per 1s step
        return {"goodput_per_dollar": round(goodput / max(dollars, 1e-9), 1),
                "serve_tokens_per_dollar": round(
                    serve_tokens / max(serve_dollars, 1e-9), 1),
                "dollars": round(dollars, 2),
                "preempted": preempted,
                "gang_pool": gang.pool if gang is not None else None,
                "placements": len(sched.placements())}

    results = {policy: drive(policy) for policy in (HETERO, ROUND_ROBIN)}
    for policy in (HETERO, ROUND_ROBIN):
        r = results[policy]
        _emit({"metric": "scheduler_goodput_per_dollar", "policy": policy,
               "value": r["goodput_per_dollar"], "unit": "eff/$",
               "serve_tokens_per_dollar": r["serve_tokens_per_dollar"],
               "dollars": r["dollars"], "preempted": r["preempted"],
               "gang_pool": r["gang_pool"],
               "pools": pools, "horizon_s": horizon_s, "backend": "none"})
    ratio = (results[HETERO]["goodput_per_dollar"]
             / max(results[ROUND_ROBIN]["goodput_per_dollar"], 1e-9))
    token_ratio = (results[HETERO]["serve_tokens_per_dollar"]
                   / max(results[ROUND_ROBIN]["serve_tokens_per_dollar"],
                         1e-9))
    _emit({"metric": "scheduler_hetero_vs_rr", "value": round(ratio, 3),
           "unit": "x", "serve_tokens_ratio": round(token_ratio, 3),
           "pools": pools, "horizon_s": horizon_s, "backend": "none"})
    return 0 if ratio > 1.0 and token_ratio > 1.0 else 1


def run_northstar_bench() -> int:
    """The NORTH-STAR metric (BASELINE.md: "pod schedule -> first-JAX-step
    latency"), control-plane half, measured hermetically: full kubelet
    stack (fake cloud over real HTTP, node+pod controllers, provider
    loops), N pods scheduled sequentially, schedule->Running wall time
    each. The reference's floor is its 30s poll loops (BASELINE.md
    timing table; worst-case ~30s before a deploy even starts) — this
    build deploys on the create event and watches status, so the p50
    lands in fractions of a second. CPU-only: no TPU needed, the metric
    is the CONTROL PLANE's."""
    import statistics

    from k8s_runpod_kubelet_tpu.cloud import HttpTransport, TpuClient
    from k8s_runpod_kubelet_tpu.cloud.fake_server import FakeTpuServer
    from k8s_runpod_kubelet_tpu.config import Config
    from k8s_runpod_kubelet_tpu.gang import (GangExecutor,
                                             InMemoryWorkerTransport)
    from k8s_runpod_kubelet_tpu.kube import FakeKubeClient
    from k8s_runpod_kubelet_tpu.kube import objects as ko
    from k8s_runpod_kubelet_tpu.node import NodeController, PodController
    from k8s_runpod_kubelet_tpu.provider import Provider

    n_pods = int(_arg_value("--pods", "12"))
    server = FakeTpuServer(provision_delay_s=0.0).start()
    kube = FakeKubeClient()
    cfg = Config(node_name="virtual-tpu", zone="us-central2-b",
                 reconcile_interval_s=0.2, notify_interval_s=0.2,
                 pending_retry_interval_s=0.5, cleanup_interval_s=5.0)
    tpu = TpuClient(HttpTransport(server.base_url, token="bench"),
                    "bench-proj", cfg.zone)
    provider = Provider(cfg, kube, tpu,
                        gang_executor=GangExecutor(InMemoryWorkerTransport()))
    nc = NodeController(kube, provider, status_interval_s=5.0)
    pc = PodController(kube, provider, cfg.node_name, resync_interval_s=5.0)
    nc.start()
    pc.start()
    provider.start()
    lats = []
    try:
        for i in range(n_pods):
            name = f"ns-bench-{i}"
            pod = {"apiVersion": "v1", "kind": "Pod",
                   "metadata": {"name": name, "namespace": "default"},
                   "spec": {"nodeName": "virtual-tpu",
                            "restartPolicy": "Never",
                            "containers": [{
                                "name": "train",
                                "image": "gcr.io/bench/maxtext:latest",
                                "resources": {"limits":
                                              {"google.com/tpu": "16"}}}]}}
            t0 = time.perf_counter()
            kube.create_pod(pod)
            deadline = t0 + 30.0
            while time.perf_counter() < deadline:
                if ko.phase(kube.get_pod("default", name)) == "Running":
                    break
                time.sleep(0.005)
            else:
                _emit({"metric": "northstar_schedule_to_running_s",
                       "value": None, "error": f"pod {name} never Running"})
                return 1
            lats.append(time.perf_counter() - t0)
    finally:
        provider.stop()
        pc.stop()
        nc.stop()
        server.stop()
    lats.sort()
    _emit({"metric": "northstar_schedule_to_running_s",
           "value": round(statistics.median(lats), 3), "unit": "s",
           # with tens of pods a "p99" would just be the max — report the
           # honest statistic under its honest name
           "max": round(lats[-1], 3),
           "mean": round(statistics.mean(lats), 3),
           "pods": n_pods, "chips_per_pod": 16, "workers_per_pod": 4,
           "reference_floor_s": 30.0,
           "vs_reference_floor": round(30.0 / statistics.median(lats), 1),
           "note": "schedule->gang-Running, hermetic fake cloud (real "
                   "HTTP); the reference's 30s poll loops bound ITS floor "
                   "(BASELINE.md) — deploy-on-event + watch-driven status "
                   "is the structural win"})
    return 0


def run_mla_bench() -> int:
    """MLA absorbed decode vs a like-for-like standard QKVO block,
    wall-clock on the chip (the AOT cells bound these; this measures).
    One JSON line per program + the ratio."""
    _force_platform_from_env()
    import jax
    import jax.numpy as jnp
    from k8s_runpod_kubelet_tpu.ops.mla import (init_mla_cache,
                                                init_mla_params,
                                                mla_decode_step)
    from k8s_runpod_kubelet_tpu.ops.rope import apply_rope, rope_frequencies

    if jax.default_backend() != "tpu":
        _emit({"metric": "mla_decode_speedup", "value": None,
               "error": f"mla bench needs a TPU, got {jax.default_backend()!r}"})
        return 1
    b, e, h, dh, dr, r, cache_len = 8, 2048, 16, 128, 64, 512, 2048
    key = jax.random.PRNGKey(0)
    params = init_mla_params(key, embed_dim=e, n_heads=h, head_dim=dh,
                             latent_dim=r, rope_dim=dr, dtype=jnp.bfloat16)
    cos, sin = rope_frequencies(dr, max_seq_len=cache_len)
    cache = init_mla_cache(b, cache_len, latent_dim=r, rope_dim=dr,
                           dtype=jnp.bfloat16)
    # mostly-full cache: decode reads scale with committed length
    cache["index"] = jnp.full((b,), cache_len - 64, jnp.int32)
    h1 = jax.random.normal(key, (b, 1, e), jnp.bfloat16)
    step = jax.jit(lambda h1, p, c: mla_decode_step(h1, p, c, cos, sin),
                   donate_argnums=(2,))
    out, cache = step(h1, params, cache)        # compile + warm
    out.block_until_ready()
    t0 = time.perf_counter()
    for _ in range(50):
        out, cache = step(h1, params, cache)    # donated cache threads through
    out.block_until_ready()
    t_mla = (time.perf_counter() - t0) / 50
    _emit({"metric": "mla_decode_ms", "value": round(t_mla * 1e3, 3),
           "unit": "ms", "tok_s": round(b / t_mla, 1)})

    ks = jax.random.split(key, 5)
    wq, wk, wv = (jax.random.normal(ks[i], (e, h * dh), jnp.bfloat16) * 0.02
                  for i in range(3))
    wo = jax.random.normal(ks[3], (h * dh, e), jnp.bfloat16) * 0.02
    kc = jnp.zeros((b, cache_len, h, dh), jnp.bfloat16)
    vc = jnp.zeros((b, cache_len, h, dh), jnp.bfloat16)
    idx = jnp.full((b,), cache_len - 64, jnp.int32)
    cos2, sin2 = rope_frequencies(dh, max_seq_len=cache_len)

    @jax.jit
    def std_step(h1, kc, vc):
        q = (h1 @ wq).reshape(b, 1, h, dh)
        k1 = (h1 @ wk).reshape(b, 1, h, dh)
        v1 = (h1 @ wv).reshape(b, 1, h, dh)
        pos = idx[:, None]
        q = apply_rope(q, cos2, sin2, pos)
        k1 = apply_rope(k1, cos2, sin2, pos)
        rows = jnp.arange(b)
        kc = kc.at[rows, idx].set(k1[:, 0])
        vc = vc.at[rows, idx].set(v1[:, 0])
        scores = jnp.einsum("bohd,blhd->bhol", q, kc) * dh ** -0.5
        live = (jnp.arange(cache_len)[None] <= idx[:, None])[:, None, None]
        scores = jnp.where(live, scores.astype(jnp.float32), -jnp.inf)
        p = jax.nn.softmax(scores, axis=-1).astype(h1.dtype)
        o = jnp.einsum("bhol,blhd->bohd", p, vc).reshape(b, 1, h * dh)
        return o @ wo, kc, vc

    std_step(h1, kc, vc)[0].block_until_ready()
    t0 = time.perf_counter()
    for _ in range(50):
        out, kc, vc = std_step(h1, kc, vc)
    out.block_until_ready()
    t_std = (time.perf_counter() - t0) / 50
    _emit({"metric": "std_attn_decode_ms", "value": round(t_std * 1e3, 3),
           "unit": "ms", "tok_s": round(b / t_std, 1)})
    _emit({"metric": "mla_decode_speedup", "value": round(t_std / t_mla, 2),
           "unit": "x", "note": "like-for-like QKVO block vs absorbed MLA, "
                                "16x128 heads, latent 512+64, cache 2048"})
    return 0


def main() -> int:
    quick = "--quick" in sys.argv
    if "--mla" in sys.argv:
        return run_mla_bench()
    if "--northstar" in sys.argv:
        return run_northstar_bench()
    if "--attn" in sys.argv:
        return run_attn_bench()
    if "--econ" in sys.argv:
        return run_econ_bench()
    if "--mfu-sweep" in sys.argv:
        return run_mfu_sweep()
    if "--attn-tune" in sys.argv:
        return run_attn_tune()
    if "--paged-attn" in sys.argv:
        return run_paged_attn_bench(smoke="--smoke" in sys.argv)
    if "--disagg" in sys.argv:
        return run_disagg_bench(smoke="--smoke" in sys.argv)
    if "--chunked" in sys.argv:
        return run_chunked_bench(smoke="--smoke" in sys.argv)
    if "--handoff-path" in sys.argv:
        return run_handoff_path_bench(smoke="--smoke" in sys.argv)
    if "--kv-fabric" in sys.argv:
        return run_kv_fabric_bench(smoke="--smoke" in sys.argv)
    if "--flight-recorder" in sys.argv:
        return run_flight_recorder_bench(smoke="--smoke" in sys.argv)
    if "--scheduler" in sys.argv:
        return run_scheduler_bench(smoke="--smoke" in sys.argv)
    if "--cost" in sys.argv:
        return run_cost_bench(smoke="--smoke" in sys.argv)
    if "--ring-flash" in sys.argv:
        return run_ring_flash_check()
    if "--spec-drift" in sys.argv:
        return run_spec_drift()
    if "--watch" in sys.argv:
        return run_watch()
    if "--serve" in sys.argv:
        return run_serve_bench(quick)
    if "--run" in sys.argv:
        result = run_bench(quick, expect_tpu="--expect-tpu" in sys.argv)
        _emit(result)
        return 0 if result.get("value") is not None else 1
    return orchestrate(quick)


if __name__ == "__main__":
    sys.exit(main())
